"""Interactive what-if replay: speculative queries against a live mirror
(ISSUE 12 tentpole — the digital twin's question-answering layer).

The replay core is orders of magnitude faster than real time
(BENCH_ENGINE_r09/r11); this module spends that speed *online*.  A
paused engine (:meth:`Simulator.run_until`) is a mirror of cluster state
at some instant; each **query** forks it (:meth:`Simulator.fork`),
applies one speculative mutation, replays a bounded horizon, and returns
the **attributed delta** against a mutation-free baseline fork of the
same horizon — JCT, goodput decomposition, and (when attribution is
armed) the PR-5 delay-by-cause split, so the answer is not just "admit
it to pod 3" but *what that choice costs and where the time goes*.

Query types (plain picklable dicts — they cross process boundaries):

- ``admit`` — "admit this job (where)?": a synthetic job spec, optionally
  pinned to a candidate pod (:meth:`Simulator.inject_admit`); candidates
  fan out as independent queries;
- ``drain`` — "drain this scope now or later?": a synthetic maintenance
  outage down the ordinary fault path (:meth:`Simulator.inject_drain`);
- ``policy-swap`` — "what if we ran SRTF instead?"
  (:meth:`Simulator.swap_policy`).

Concurrency (:class:`~gpuschedule_tpu.sim.pool.WorkerPool`): each worker
restores the shipped mirror bytes ONCE, pre-warms the baseline for the
default horizon, then serves queries by in-memory fork — the
"restore once, fork many" contract that makes per-query latency the fork
+ bounded-replay cost instead of a full state ship.  ``workers=0`` (the
default) serves forks straight off the paused engine in-process: same
arithmetic, no processes — queries are deterministic, so serial and
pooled evaluation return identical result documents (modulo latency
readings; pinned by tests/test_whatif.py).

Observability: per-query latency lands in the metrics-registry histogram
``whatif_query_latency_ms{kind}``, and :func:`append_history` writes one
PR-10 history row per query (kind ``whatif``), so SLO trends of the twin
itself are one ``history trend`` away.
"""

from __future__ import annotations

import contextlib
import copy
import math
import threading
import time
from typing import Dict, List, Optional, Sequence

from gpuschedule_tpu.sim.job import Job
from gpuschedule_tpu.obs.fleet import (
    active as _fleet_active,
    task_span as _task_span,
)
from gpuschedule_tpu.obs.tracer import NULL_SPAN as _NULL_SPAN

QUERY_KINDS = ("admit", "drain", "policy-swap")


class AdmissionError(RuntimeError):
    """Raised by :meth:`WhatIfService.admitted` when the bounded
    in-flight queue is full — the serving layer's backpressure signal
    (HTTP 429 at the edge, ISSUE 18)."""


# --------------------------------------------------------------------- #
# query evaluation core (shared by the serial path and pool workers)


def _result_doc(res) -> dict:
    """The picklable slice of one fork's SimResult a delta needs."""
    return {
        "avg_jct_s": res.avg_jct,
        "makespan_s": res.makespan,
        "p95_queueing_delay_s": res.p95_queueing_delay,
        "num_finished": res.num_finished,
        "num_unfinished": res.num_unfinished,
        "goodput": dict(res.goodput),
        "delay_by_cause": dict(res.delay_by_cause),
    }


def _delta_doc(base: dict, var: dict) -> dict:
    """Per-metric variant-minus-baseline diff; dict-valued metrics diff
    per key over the union (a cause/leg absent on one side reads 0)."""
    out: dict = {}
    for key, bv in base.items():
        vv = var[key]
        if isinstance(bv, dict):
            keys = sorted(set(bv) | set(vv))
            out[key] = {
                k: vv.get(k, 0.0) - bv.get(k, 0.0) for k in keys
            }
        else:
            out[key] = vv - bv
    return out


def _bound(fork, horizon: float) -> None:
    fork.max_time = min(fork.max_time, fork.now + horizon)


def validate_query(q: dict) -> dict:
    kind = q.get("kind")
    if kind not in QUERY_KINDS:
        raise ValueError(
            f"unknown what-if query kind {kind!r}; known: {QUERY_KINDS}"
        )
    if kind == "admit":
        if not int(q.get("chips", 0)) > 0:
            raise ValueError("admit query needs chips > 0")
        if not float(q.get("duration", 0.0)) > 0.0:
            raise ValueError("admit query needs duration > 0")
    elif kind == "drain":
        scope = q.get("scope")
        if not scope or len(scope) < 2:
            raise ValueError(
                "drain query needs a scope like ['pod', 7]"
            )
    elif kind == "policy-swap":
        if not q.get("policy"):
            raise ValueError("policy-swap query needs a policy name")
    return q


def normalize_query(q: dict) -> dict:
    """Coerce a wire-format query's numeric fields to the exact types
    the CLI spec parsers produce (chips int, duration/at float, scope
    members int), so a served result document — which echoes the query —
    never depends on whether the asker sent ``3600`` or ``3600.0``
    (ISSUE 18: the echo is part of the byte-identity surface)."""
    q = dict(q)
    kind = q.get("kind")
    if kind == "admit":
        if "chips" in q:
            q["chips"] = int(q["chips"])
        if "duration" in q:
            q["duration"] = float(q["duration"])
        if q.get("pod") is not None:
            q["pod"] = int(q["pod"])
        if q.get("at") is not None:
            q["at"] = float(q["at"])
    elif kind == "drain":
        scope = q.get("scope")
        if isinstance(scope, (list, tuple)) and scope:
            q["scope"] = [scope[0], *(int(s) for s in scope[1:])]
        if q.get("at") is not None:
            q["at"] = float(q["at"])
        if q.get("duration") is not None:
            q["duration"] = float(q["duration"])
    return q


def apply_query(fork, q: dict) -> Optional[Job]:
    """Apply one validated query's mutation to a fork; returns the
    injected job for ``admit`` (its outcome rides the result)."""
    kind = q["kind"]
    if kind == "admit":
        job = Job(
            q.get("job_id") or "whatif-admit",
            fork.now,
            num_chips=int(q["chips"]),
            duration=float(q["duration"]),
            model_name=q.get("model") or "transformer-tiny",
        )
        pod = q.get("pod")
        return fork.inject_admit(
            job,
            t=q.get("at"),
            pin={"pod": int(pod)} if pod is not None else None,
        )
    if kind == "drain":
        scope = q["scope"]
        fork.inject_drain(
            (scope[0], *(int(s) for s in scope[1:])),
            t=q.get("at"),
            duration=float(q.get("duration", math.inf)),
        )
        return None
    # policy-swap
    from gpuschedule_tpu.policies import make_policy

    fork.swap_policy(make_policy(q["policy"], **(q.get("policy_args") or {})))
    return None


def evaluate_query(fork_fn, q: dict, horizon: float, base: dict) -> dict:
    """One speculative replay: ``fork_fn()`` yields a fresh independent
    clone of the mirror (``sim.fork`` for one-shot use; the service
    clones from cached mirror bytes — unpickle-only, half the fork
    cost); mutate it, run the bounded horizon, diff against the
    (already computed) baseline doc.

    When a fleet task harness is armed (ISSUE 16) the phases land as
    child spans carrying the propagated trace context — fork / mutate /
    replay / diff, with ``restore`` nested under fork when the fork
    clones from mirror bytes — and the evaluation bumps the harness's
    ``whatif_queries_total{kind}`` counter.  Both hooks are no-ops when
    disarmed (one module-global read), and the counter lives on the
    harness registry precisely so that the serial and pooled merged
    registries come out identical: one increment per query, wherever
    the query ran."""
    harness = _fleet_active()
    if harness is not None:
        harness.registry.counter(
            "whatif_queries_total",
            "what-if queries evaluated",
            labelnames=("kind",),
        ).labels(q["kind"]).inc()
    with _task_span("fork", kind=q["kind"]):
        fork = fork_fn()
    at = fork.now
    _bound(fork, horizon)
    q_at = q.get("at")
    if q_at is not None and float(q_at) > fork.max_time:
        # past the cutoff the mutation would sit unapplied in the heap
        # and the delta read as a spurious ~zero ("admitting costs
        # nothing") instead of "outside the evaluated window"
        raise ValueError(
            f"query at={q_at} is beyond the bounded replay window "
            f"(ends at t={fork.max_time}); raise the horizon or move "
            "the query earlier"
        )
    with _task_span("mutate", kind=q["kind"]):
        injected = apply_query(fork, q)
    with _task_span("replay"):
        res = fork.run()
    with _task_span("diff"):
        var = _result_doc(res)
    doc = {
        "query": dict(q),
        "at_s": at,
        "horizon_s": horizon,
        "base": base,
        "variant": var,
        "delta": _delta_doc(base, var),
    }
    if injected is not None:
        out = {
            "job_id": injected.job_id,
            "end_state": injected.state.value,
            "executed_work_s": injected.executed_work,
        }
        if injected.first_start_time is not None:
            out["wait_s"] = injected.first_start_time - injected.submit_time
        if injected.end_time is not None:
            out["jct_s"] = injected.end_time - injected.submit_time
        if injected.attrib:
            out["blame"] = dict(injected.attrib)
        doc["admitted"] = out
    return doc


def baseline_doc(fork_fn, horizon: float) -> dict:
    """The mutation-free comparator: a bare fork run to the same bounded
    horizon.  Deterministic, so every evaluator (serial or any worker)
    derives the identical doc."""
    fork = fork_fn()
    _bound(fork, horizon)
    return _result_doc(fork.run())


# --------------------------------------------------------------------- #
# pool-worker half: module state warmed once per worker process

# lint: allow[GS601] deliberately process-local: each pool worker holds its own restored mirror bytes (ISSUE 12)
_MIRROR_BYTES: Optional[bytes] = None
# lint: allow[GS601] deliberately process-local: each pool worker warms its own baseline cache after restoring the broadcast mirror (ISSUE 12)
_BASELINES: Dict[float, dict] = {}


def _worker_fork():
    from gpuschedule_tpu.sim.snapshot import clone_from_state_bytes

    with _task_span("restore"):
        return clone_from_state_bytes(_MIRROR_BYTES)


def _load_mirror(data: bytes, horizon: float) -> bool:
    """WorkerPool broadcast target: keep the shipped engine state bytes
    (each query clones from them — unpickle-only forks) and pre-warm
    the default-horizon baseline, so the first query pays only its own
    fork + replay."""
    global _MIRROR_BYTES
    _MIRROR_BYTES = data
    _BASELINES.clear()
    _BASELINES[horizon] = baseline_doc(_worker_fork, horizon)
    return True


def _eval_task(q: dict, horizon: float) -> dict:
    """WorkerPool map target: one query against this worker's mirror."""
    if _MIRROR_BYTES is None:
        raise RuntimeError("what-if worker has no mirror loaded")
    base = _BASELINES.get(horizon)
    if base is None:
        # lazy warm for a non-preloaded horizon: setup cost, untimed —
        # the same rule _eval_local follows
        base = _BASELINES[horizon] = baseline_doc(_worker_fork, horizon)
    # lint: allow[GS101] query-latency measurement is wall-clock by design; the replay itself never reads it
    t0 = time.perf_counter()
    doc = evaluate_query(_worker_fork, q, horizon, base)
    doc["latency_s"] = time.perf_counter() - t0  # lint: allow[GS101] same latency surface as above
    return doc


# --------------------------------------------------------------------- #
# the service


class WhatIfService:
    """Speculative-query front end over one paused engine.

    ``workers >= 1`` ships the mirror to a persistent
    :class:`~gpuschedule_tpu.sim.pool.WorkerPool` (restore once per
    worker, fork per query, crash/retry per the pool contract);
    ``workers=0`` evaluates in-process off ``sim`` itself.  ``registry``
    (an obs MetricsRegistry) arms the per-query latency histogram, and
    hands the pool its lifecycle counters
    (``pool_worker_respawns_total`` / ``pool_task_retries_total``).

    ``fleet`` (a :class:`gpuschedule_tpu.obs.fleet.FleetCollector`,
    ISSUE 16) arms cross-process tracing: each task ships a trace-context
    envelope, every worker (or the in-process evaluator) runs a child
    telemetry harness whose spans/counters ride back with the result,
    and :meth:`evaluate` wraps its own phases in parent spans
    (enqueue / dispatch / reassemble).  Result documents are bytewise
    unaffected — telemetry travels out of band.
    """

    def __init__(
        self,
        sim,
        *,
        horizon: float,
        workers: int = 0,
        registry=None,
        fleet=None,
        max_retries: int = 2,
        backoff_s: float = 1.0,
        max_inflight: Optional[int] = None,
    ):
        if not horizon > 0.0:
            raise ValueError(f"horizon must be > 0, got {horizon}")
        self.sim = sim
        self.horizon = float(horizon)
        self.queries_served = 0
        self.workers = int(workers) if workers and workers >= 1 else 0
        # admission control (ISSUE 18): the serving daemon bounds
        # concurrent askers to the pool's real capacity — default twice
        # the evaluator count (one in flight, one queued behind it)
        if max_inflight is None:
            max_inflight = 2 * max(1, self.workers)
        self.max_inflight = int(max_inflight)
        if self.max_inflight < 1:
            raise ValueError(
                f"max_inflight must be >= 1, got {self.max_inflight}"
            )
        self.rejections = 0
        self._inflight = 0
        self._adm_lock = threading.Lock()
        self._dispatch_lock = threading.Lock()
        self._registry = registry
        self._rejected_counter = None
        self._fleet = fleet
        self._latency = None
        if registry is not None:
            from gpuschedule_tpu.obs.metrics import LATENCY_BUCKETS_MS

            self._latency = registry.histogram(
                "whatif_query_latency_ms",
                "What-if query latency (milliseconds)",
                labelnames=("kind",),
                buckets=LATENCY_BUCKETS_MS,
            )
        self._pool = None
        self._baselines: Dict[float, dict] = {}
        self._bytes: Optional[bytes] = None
        if workers and workers >= 1:
            from gpuschedule_tpu.sim.pool import WorkerPool
            from gpuschedule_tpu.sim.snapshot import state_to_bytes

            # cache the serialized mirror for any later in-process
            # fork/warm too — the dump is the expensive half
            self._bytes = state_to_bytes(sim)
            self._pool = WorkerPool(
                workers, max_retries=max_retries, backoff_s=backoff_s,
                registry=registry,
            )
            self._pool.broadcast(_load_mirror, self._bytes, self.horizon)

    # ------------------------------------------------------------------ #

    def _fork(self):
        """In-process per-query fork, from cached mirror bytes (the
        paused engine's state is invariant between queries, so the dump
        half of the fork round trip happens once)."""
        from gpuschedule_tpu.sim.snapshot import (
            clone_from_state_bytes,
            state_to_bytes,
        )

        if self._bytes is None:
            self._bytes = state_to_bytes(self.sim)
        with _task_span("restore"):
            return clone_from_state_bytes(self._bytes)

    def warm(self, horizon: Optional[float] = None) -> dict:
        """Ensure the in-process baseline for ``horizon`` exists (pool
        workers pre-warm at load time); returns the baseline doc."""
        h = self.horizon if horizon is None else float(horizon)
        base = self._baselines.get(h)
        if base is None:
            base = self._baselines[h] = baseline_doc(self._fork, h)
        return base

    def _eval_local(self, q: dict, horizon: float) -> dict:
        # warm OUTSIDE the timer: the one-off baseline replay is setup
        # cost (pool workers pre-warm at load), not this query's latency
        # — else the first serial query reports ~2x and the SLO
        # telemetry becomes mode-dependent
        base = self.warm(horizon)
        # lint: allow[GS101] query-latency measurement is wall-clock by design; the replay itself never reads it
        t0 = time.perf_counter()
        doc = evaluate_query(self._fork, q, horizon, base)
        doc["latency_s"] = time.perf_counter() - t0  # lint: allow[GS101] same latency surface as above
        return doc

    def evaluate(self, queries: Sequence[dict]) -> List[dict]:
        """Evaluate ``queries`` (result order = query order, whatever the
        pool interleaving), observing each latency into the histogram.

        With a fleet collector armed, the three parent phases land as
        spans on the collector's tracer — enqueue (validation / task
        building), dispatch (the pool map or in-process loop, with each
        task wrapped in a trace-context envelope), reassemble (latency
        observation over the ordered results) — and every evaluator-side
        span/counter rides back through the collector.  The result list
        itself is byte-identical either way."""
        fleet = self._fleet
        if fleet is None:
            tasks = [
                (validate_query(dict(q)), self.horizon) for q in queries
            ]
            if self._pool is not None:
                out = self._pool.map(_eval_task, tasks)
            else:
                out = [self._eval_local(q, h) for q, h in tasks]
        else:
            with fleet.span("enqueue", tasks=len(queries)):
                tasks = [
                    (validate_query(dict(q)), self.horizon) for q in queries
                ]
            with fleet.span("dispatch", tasks=len(tasks)):
                if self._pool is not None:
                    out = self._pool.map(_eval_task, tasks, fleet=fleet)
                else:
                    out = [
                        fleet.run_local(self._eval_local, i, (q, h))
                        for i, (q, h) in enumerate(tasks)
                    ]
        with (fleet.span("reassemble", tasks=len(out))
              if fleet is not None else _NULL_SPAN):
            self.queries_served += len(out)
            if self._latency is not None:
                for doc in out:
                    self._latency.labels(kind=doc["query"]["kind"]).observe(
                        1000.0 * doc["latency_s"]
                    )
        return out

    # ------------------------------------------------------------------ #
    # bounded admission (ISSUE 18): backpressure for concurrent askers

    @property
    def inflight(self) -> int:
        """Admitted-but-unfinished queries right now."""
        return self._inflight

    @contextlib.contextmanager
    def admitted(self):
        """Hold one slot in the bounded in-flight queue for the duration
        of the block; raises :class:`AdmissionError` (and counts the
        rejection into ``whatif_rejected_total``) when all
        ``max_inflight`` slots are taken.  Non-blocking by design — the
        serving edge turns the refusal into HTTP 429 rather than letting
        askers pile up behind a saturated pool."""
        with self._adm_lock:
            if self._inflight >= self.max_inflight:
                self.rejections += 1
                if self._registry is not None:
                    if self._rejected_counter is None:
                        self._rejected_counter = self._registry.counter(
                            "whatif_rejected_total",
                            "what-if queries refused by admission "
                            "control (in-flight queue full)",
                        )
                    self._rejected_counter.inc()
                raise AdmissionError(
                    f"what-if admission queue full "
                    f"({self.max_inflight} in flight); retry later"
                )
            self._inflight += 1
        try:
            yield self
        finally:
            with self._adm_lock:
                self._inflight -= 1

    def evaluate_admitted(self, queries: Sequence[dict]) -> List[dict]:
        """:meth:`evaluate` made safe for concurrent callers: dispatch is
        serialized under one lock (the pool map and the engine's
        in-process forks are not reentrant), and the bounded admission
        gate upstream keeps the wait behind it short by construction."""
        with self._dispatch_lock:
            return self.evaluate(queries)

    def pool_stats(self) -> dict:
        """Pool-lifecycle summary for the history "pool" row and the
        serving ``/status`` page: worker count plus the respawn / retry
        totals the pool counted across this service's queries.  In
        serial mode (``workers=0``) there is no pool to crash, so the
        counters read an honest zero rather than a blank (ISSUE 18
        satellite — ``/status`` never blanks for workers=0)."""
        if self._pool is None:
            return {"workers": self.workers, "respawns": 0, "retries": 0}
        return {
            "workers": self.workers,
            "respawns": self._pool.respawns,
            "retries": self._pool.retries,
        }

    def close(self) -> None:
        if self._pool is not None:
            self._pool.close()
            self._pool = None

    def __enter__(self) -> "WhatIfService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------------- #
# CLI spec parsing (the `whatif` subcommand's query grammar)


def _pairs(spec: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, sep, raw = pair.partition("=")
        if not sep:
            raise ValueError(f"bad what-if spec entry {pair!r} (want k=v)")
        out[key.strip().replace("-", "_")] = raw.strip()
    return out


def parse_admit_spec(spec: str) -> List[dict]:
    """``--admit chips=8,duration=3600[,model=M][,at=T][,pods=0:2:5]``
    — one unit query per candidate pod in ``pods`` (colon-separated),
    or a single unpinned query (the policy places it) without."""
    kv = _pairs(spec)
    known = {"chips", "duration", "model", "at", "pods", "job_id"}
    unknown = sorted(set(kv) - known)
    if unknown:
        raise ValueError(
            f"unknown --admit keys {unknown}; known: {sorted(known)}"
        )
    if "chips" not in kv or "duration" not in kv:
        raise ValueError("--admit needs at least chips= and duration=")
    base = {
        "kind": "admit",
        "chips": int(kv["chips"]),
        "duration": float(kv["duration"]),
    }
    if "model" in kv:
        base["model"] = kv["model"]
    if "at" in kv:
        base["at"] = float(kv["at"])
    if "job_id" in kv:
        base["job_id"] = kv["job_id"]
    pods = kv.get("pods")
    if pods is None:
        return [validate_query(base)]
    return [
        validate_query({**base, "pod": int(p)})
        for p in pods.split(":") if p != ""
    ]


def parse_drain_spec(spec: str) -> dict:
    """``--drain pod=7[,at=T][,duration=S]`` — duration defaults to a
    permanent drain (``inf``)."""
    kv = _pairs(spec)
    known = {"pod", "at", "duration"}
    unknown = sorted(set(kv) - known)
    if unknown:
        raise ValueError(
            f"unknown --drain keys {unknown}; known: {sorted(known)}"
        )
    if "pod" not in kv:
        raise ValueError("--drain needs pod=")
    q = {"kind": "drain", "scope": ["pod", int(kv["pod"])]}
    if "at" in kv:
        q["at"] = float(kv["at"])
    if "duration" in kv:
        q["duration"] = float(kv["duration"])
    return validate_query(q)


# --------------------------------------------------------------------- #
# observability plumbing


def result_document(sim, results: Sequence[dict], *,
                    requested_at: float, horizon: float, pool: int,
                    run_meta: dict) -> dict:
    """The what-if answer document — factored out of the ``whatif`` CLI
    so the serving daemon (ISSUE 18) and the offline command build the
    SAME structure from the same parts: mirror identity + position, the
    latency summary, and the ordered per-query delta docs.  Byte
    identity between the two paths (modulo the wall-clock latency
    readings — see :func:`canonical_document`) is pinned by
    tests/test_serve.py."""
    from gpuschedule_tpu.faults.sweep import jsonable

    return jsonable({
        "at_s": sim.now,
        "requested_at_s": requested_at,
        "horizon_s": horizon,
        "pool": pool,
        "policy": run_meta["policy"],
        "run_id": run_meta["run_id"],
        "config_hash": run_meta["config_hash"],
        "mirror": {
            "running": len(sim.running),
            "pending": len(sim.pending),
            "finished": len(sim.finished),
        },
        "latency_ms": latency_summary(results),
        "queries": results,
    })


def canonical_document(doc: dict) -> dict:
    """The wall-clock-free projection of a result document: every field
    is a pure function of (world, mirror instant, queries) EXCEPT the
    latency readings, which are measurements of this host right now.
    Dropping them (the summary keeps its ``count``) leaves the byte
    surface the served-vs-offline identity contract compares."""
    out = copy.deepcopy(doc)
    out["latency_ms"] = {"count": out["latency_ms"]["count"]}
    for q in out["queries"]:
        q.pop("latency_s", None)
    return out


def latency_summary(results: Sequence[dict]) -> dict:
    """p50/p95/max over the per-query latencies, in milliseconds."""
    from gpuschedule_tpu.obs.metrics import exact_quantile

    lats = sorted(1000.0 * r["latency_s"] for r in results)
    if not lats:
        return {"count": 0}
    return {
        "count": len(lats),
        "p50_ms": exact_quantile(lats, 0.50),
        "p95_ms": exact_quantile(lats, 0.95),
        "max_ms": lats[-1],
    }


def append_history(store_path, results: Sequence[dict], *,
                   run_meta: Optional[dict] = None,
                   pool_stats: Optional[dict] = None) -> int:
    """One PR-10 history row per query (kind ``whatif``, label = query
    kind), so the twin's own serving latency and the deltas it reported
    trend across invocations like any other result.  ``pool_stats``
    (:meth:`WhatIfService.pool_stats`, ISSUE 16) appends one extra row
    labeled ``pool`` carrying the pool-lifecycle counters — worker
    count, respawns, retries — so fleet health trends beside query
    latency; ``None`` (the in-process path) adds nothing."""
    from gpuschedule_tpu.obs.history import HistoryStore

    meta = run_meta or {}
    n = 0
    with HistoryStore(store_path) as store:
        for doc in results:
            q = doc["query"]
            metrics = {
                "latency_ms": 1000.0 * doc["latency_s"],
                "at_s": doc["at_s"],
                "horizon_s": doc["horizon_s"],
                "delta_avg_jct_s": doc["delta"]["avg_jct_s"],
                "delta_num_finished": doc["delta"]["num_finished"],
            }
            admitted = doc.get("admitted")
            if admitted is not None and "jct_s" in admitted:
                metrics["admit_jct_s"] = admitted["jct_s"]
            store.append(
                "whatif",
                run_id=meta.get("run_id", ""),
                config_hash=meta.get("config_hash", ""),
                policy=meta.get("policy", ""),
                seed=meta.get("seed"),
                label=q["kind"],
                metrics=metrics,
            )
            n += 1
        if pool_stats is not None:
            store.append(
                "whatif",
                run_id=meta.get("run_id", ""),
                config_hash=meta.get("config_hash", ""),
                policy=meta.get("policy", ""),
                seed=meta.get("seed"),
                label="pool",
                metrics={
                    "workers": pool_stats["workers"],
                    "respawns": pool_stats["respawns"],
                    "retries": pool_stats["retries"],
                    "queries": len(results),
                },
            )
            n += 1
    return n
