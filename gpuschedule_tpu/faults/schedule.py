"""Deterministic seeded fault-schedule generation.

The Philly study this repo replays (ATC'19 [P]) is as much about failures
as about queueing: roughly a third of jobs do not complete successfully,
and the paper's failure analysis attributes a large share of lost goodput
to hardware faults and restarts.  This module generates the *hardware*
half of that story: a reproducible schedule of ``FaultRecord(time, scope,
duration, kind)`` events a :class:`~gpuschedule_tpu.sim.engine.Simulator`
injects as ``_FAULT``/``_REPAIR`` event pairs.

Five stochastic fault processes (plus deterministic maintenance), each
with its own RNG stream:

- **MTBF chip failures** (``kind="mtbf"``): every chip is an independent
  exponential process with mean ``mtbf`` seconds, so the fleet fails as a
  Poisson superposition at rate ``total_chips / mtbf``; each failure takes
  one topology unit offline (a TPU chip, a GPU host node — Philly's
  failure domain — or one flat-pool chip) for an exponentially distributed
  repair time with mean ``repair``.
- **Planned maintenance** (``kind="maintenance"``): deterministic windows
  every ``maintenance_period`` seconds, rotating over pods (TPU), nodes
  (GPU), or an eighth of the flat pool, each lasting
  ``maintenance_duration`` seconds.
- **Spot/preemptible revocation** (``kind="spot"``): the last
  ``spot_fraction`` of capacity (whole pods / nodes / a chip block) is
  preemptible; each spot unit is revoked at exponentially distributed
  intervals with mean ``spot_mtbf`` for a fixed ``spot_outage``.  With
  ``spot_warning > 0`` each revocation is preceded by a pre-revoke
  notice that far ahead: the engine delivers it to the gangs on the spot
  unit and the recovery model takes an *emergency checkpoint* when the
  window covers the checkpoint-write cost, shrinking the lost work from
  a full checkpoint interval to the tail of the warning window.
- **Correlated domain outages** (``kind="domain"``): real fleets fail by
  blast radius — a PDU trip takes a rack, a power event takes a pod —
  not as independent chip coin flips.  Each domain in the cluster's
  host/rack/pod hierarchy (``cluster.failure_domains()``, derived from
  the flavor's geometry) is an independent exponential process with mean
  ``domain_mtbf``; one record takes *every* chip under the domain
  offline at once (one fault event, one multi-gang revocation batch,
  one repair).
- **Straggler chips** (``kind="straggler"``): chips degrade gradually
  before they die.  Each chip (TPU) or host node (GPU) turns straggler
  at exponentially distributed intervals with mean ``straggler_mtbf``;
  while degraded it runs at ``straggler_degrade`` of its rate and the
  whole synchronous gang on it slows to the straggler's rate
  (``Job.slow_factor``) — slowed, never revoked, like PR 4's link
  degradation but on the compute side.

Seed-split rule (the reproducibility contract, shared with ``cli.py``):
one user-facing ``--seed`` governs every stochastic stream in a run.
Trace synthesis keeps the bare seed (``random.Random(seed)``, unchanged
from before faults existed), while each fault process derives its own
independent stream as ``random.Random(f"{seed}:faults:<process>")`` with
``<process>`` in ``{"mtbf", "spot", "link", "domain", "straggler"}``
(maintenance is deterministic).
String seeding hashes stably across runs and platforms, so the same seed
always yields byte-identical trace *and* fault schedules, and changing
the fault config never perturbs the trace stream (or vice versa).

Scope tuples are cluster-flavor specific (the injector hands them back to
``cluster.mark_unhealthy`` / ``cluster.repair`` verbatim):

- ``("chips", n)`` — n fungible chips of a flat pool;
- ``("chip", pod, coord)`` — one chip of a TPU torus;
- ``("box", pod, origin, shape)`` — an axis-aligned TPU sub-box;
- ``("pod", pod)`` — a whole TPU pod;
- ``("node", switch, node)`` — a whole GPU host node;
- ``("switch", switch)`` — every node under one GPU switch (the GPU
  rack-level failure domain);
- ``("link", pod)`` — a TPU pod's DCN uplink (kind ``"link"``): handled
  by the engine + net/ contention model, never by the health mask —
  multislice jobs *slow down* for the outage instead of being revoked.

Straggler records reuse the per-unit scopes (``("chip", pod, coord)`` /
``("node", switch, node)``) but are dispatched by ``kind="straggler"``
to the cluster's *degrade* mask (``mark_degraded``/``clear_degraded``),
not the health mask: a straggler chip stays allocatable, it is just
slow.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class FaultRecord:
    """One hardware outage: ``scope`` goes down at ``time`` for
    ``duration`` seconds (``inf`` = never repaired).

    ``degrade`` applies to partial-degradation kinds — ``("link", pod)``
    scopes (the fraction of the uplink's capacity that *remains* during
    the outage; 0.0 = hard outage) and ``kind="straggler"`` records (the
    fraction of the chip's rate that remains).  Both slow jobs instead
    of revoking anything.

    ``level`` names the hierarchy tier of a ``kind="domain"`` record
    (``host``/``rack``/``pod``); ``warning`` is the pre-revoke notice
    lead time of a ``kind="spot"`` record (0 = unannounced)."""

    time: float
    scope: Tuple
    duration: float
    kind: str = "mtbf"  # mtbf | maintenance | spot | link | domain | straggler
    degrade: float = 0.0
    level: str = ""
    warning: float = 0.0

    @property
    def label(self) -> str:
        """Stable human-readable scope name (Perfetto health tracks, event
        stream records); pure function of the scope tuple."""
        s = self.scope
        if s[0] == "chips":
            return f"chips[{s[1]}]"
        if s[0] == "chip":
            return f"pod{s[1]}/chip@" + ",".join(str(c) for c in s[2])
        if s[0] == "box":
            shape = "x".join(str(c) for c in s[3])
            origin = ",".join(str(c) for c in s[2])
            return f"pod{s[1]}/{shape}@{origin}"
        if s[0] == "pod":
            return f"pod{s[1]}"
        if s[0] == "node":
            return f"gpu/s{s[1]}n{s[2]}"
        if s[0] == "switch":
            return f"gpu/sw{s[1]}"
        if s[0] == "link":
            return f"dcn/pod{s[1]}"
        return str(s)


@dataclass
class FaultConfig:
    """Knobs for the three fault processes.  Defaults are all-off
    (``mtbf=inf``, no maintenance, no spot capacity): constructing a plan
    from the default config exercises the fault path with zero faults."""

    mtbf: float = math.inf              # per-chip mean time between failures (s)
    repair: float = 3600.0              # mean repair duration (s)
    # Hazard model (faults/hazard.py, ISSUE 8): hazard_shape is the
    # Weibull shape of the MTBF process — 1.0 is the memoryless default
    # (byte-identical schedules); >1 wear-out (failures cluster late),
    # <1 infant mortality.  hazard_util_weight folds runtime wear (busy
    # chip-seconds per chip) into the effective age the runtime hazard
    # SCORE uses (schedules are generated up front and cannot see
    # utilization); migrate_threshold arms the engine's proactive
    # checkpoint-and-migrate offer (inf = never).
    hazard_shape: float = 1.0
    hazard_util_weight: float = 0.0
    migrate_threshold: float = math.inf
    maintenance_period: float = 0.0     # seconds between planned windows (0 = off)
    maintenance_duration: float = 7200.0
    spot_fraction: float = 0.0          # trailing fraction of capacity that is spot
    spot_mtbf: float = 4 * 3600.0       # mean time between revocations per unit
    spot_outage: float = 1800.0         # fixed outage per revocation
    spot_warning: float = 0.0           # pre-revoke notice lead time (s, 0 = none):
                                        # the engine delivers it to the gangs on
                                        # the spot unit and the recovery model
                                        # takes an emergency checkpoint when the
                                        # window covers the write cost
    # Correlated failure domains (kind="domain"): every domain in the
    # cluster's host/rack/pod hierarchy (cluster.failure_domains()) is an
    # independent exponential process; one record takes ALL chips under
    # the domain offline at once.
    domain_mtbf: float = math.inf       # per-domain mean time between outages (s)
    domain_repair: float = 2 * 3600.0   # mean domain repair duration (s)
    # Per-level domain rate weighting (ISSUE 8 satellite): multiplies the
    # outage rate of every domain at that hierarchy level, so pod-scale
    # blast radii can be made (realistically) rarer than host blips
    # without touching the aggregate knob.  None keeps the historical
    # uniform pick byte-identical (the single-knob form is hash-pinned);
    # a dict like {"host": 4.0, "rack": 1.0, "pod": 0.25} re-weights the
    # superposition (per-domain rate = weight / domain_mtbf).
    domain_weights: Optional[Dict[str, float]] = None
    # Straggler chips (kind="straggler"): per-chip (TPU) / per-node (GPU)
    # gradual degradation — the unit keeps running at straggler_degrade of
    # its rate and the whole gang on it slows to match (never revoked).
    straggler_mtbf: float = math.inf    # per-unit mean time between onsets (s)
    straggler_repair: float = 3600.0    # mean degradation duration (s)
    straggler_degrade: float = 0.5      # residual rate fraction while degraded
    # DCN-uplink outages (kind="link", TPU fleets only): each pod's uplink
    # is an independent exponential process; an outage *degrades* the link
    # to link_degrade of its capacity instead of killing anything — the
    # contention model (net/) turns that into a multislice slowdown.
    link_mtbf: float = math.inf         # per-uplink mean time between outages (s)
    link_repair: float = 3600.0         # mean outage duration (s)
    link_degrade: float = 0.25          # residual capacity fraction during outage


def fault_horizon(jobs: Sequence, *, slack: float = 2.0) -> float:
    """Replay-length bound for schedule generation: the last submission
    plus ``slack`` times the total serial work.

    The serial-work term alone is NOT an upper bound under faults — every
    revocation adds rework (back to the last checkpoint) plus restore cost,
    and repair downtime idles capacity — so ``slack`` pads it (2x covers
    any fault mix where less than half of all chip-time is rework, far
    beyond the realistic MTBF grid).  A pathological run that still outruns
    the horizon simply sees no faults past it.  Overshoot in the other
    direction (parallel clusters finish well before serial time) only costs
    schedule entries: the engine discards records once every job has
    reached an end state.  Callers with a ``max_time`` cutoff should pass
    that instead — it is exact."""
    if not jobs:
        return 0.0
    return max(j.submit_time for j in jobs) + slack * sum(
        j.duration for j in jobs
    )


def scope_capacity(cluster, scope) -> int:
    """Chips a fault ``scope`` takes *offline* (the availability
    accounting input for sweeps).  Degrade-only scopes — uplinks and
    straggler units never leave the capacity pool — report 0; callers
    filter by record kind for those."""
    inner = getattr(cluster, "inner", cluster)
    kind = scope[0]
    if kind == "chips":
        return int(scope[1])
    if kind == "chip":
        return 1
    if kind == "box":
        return math.prod(scope[3])
    if kind == "pod":
        return inner.pod_chips
    if kind == "node":
        return inner.gpus_per_node
    if kind == "switch":
        return inner.nodes_per_switch * inner.gpus_per_node
    return 0  # link / unknown: no capacity leaves the pool


def _flavor(cluster) -> Tuple[str, object]:
    """(flavor, unwrapped cluster): 'tpu' | 'gpu' | 'flat'.  Placement
    wrappers (``PlacedTpuCluster``) delegate by ``__getattr__``, so the
    inner cluster is what carries the topology attributes."""
    inner = getattr(cluster, "inner", cluster)
    if hasattr(inner, "pod_chips") and hasattr(inner, "dims"):
        return "tpu", inner
    if hasattr(inner, "nodes_per_switch"):
        return "gpu", inner
    return "flat", inner


def generate_fault_schedule(
    cluster,
    config: FaultConfig,
    *,
    horizon: float,
    seed: int = 0,
) -> List[FaultRecord]:
    """Generate the full, time-sorted fault schedule for one replay.

    Deterministic per (cluster shape, config, horizon, seed): the MTBF and
    spot processes draw from independent ``random.Random(f"{seed}:faults:
    <process>")`` streams (module docstring seed-split rule), so two calls
    with the same arguments return byte-identical schedules.
    """
    flavor, inner = _flavor(cluster)
    records: List[FaultRecord] = []

    # -- MTBF chip failures -------------------------------------------- #
    if config.mtbf > 0 and math.isfinite(config.mtbf) and horizon > 0:
        rng = random.Random(f"{seed}:faults:mtbf")
        rate = inner.total_chips / config.mtbf
        # repair=inf means failures are permanent (duration=inf, the
        # engine's never-repaired case); repair<=0 is an instant blip
        # that still revokes overlapping gangs
        def repair_duration() -> float:
            if math.isinf(config.repair):
                return math.inf
            if config.repair > 0:
                return rng.expovariate(1.0 / config.repair)
            return 0.0

        def mtbf_scope() -> Tuple:
            if flavor == "tpu":
                pod = rng.randrange(inner.num_pods)
                coord = tuple(rng.randrange(d) for d in inner.dims)
                return ("chip", pod, coord)
            if flavor == "gpu":
                # a GPU failure takes its host node offline (the Philly
                # failure domain is the machine, not the device)
                return (
                    "node",
                    rng.randrange(inner.num_switches),
                    rng.randrange(inner.nodes_per_switch),
                )
            return ("chips", 1)

        if config.hazard_shape == 1.0:
            # memoryless (the historical process — this branch must stay
            # byte-identical draw for draw)
            t = rng.expovariate(rate)
            while t <= horizon:
                records.append(
                    FaultRecord(t, mtbf_scope(), repair_duration(), "mtbf")
                )
                t += rng.expovariate(rate)
        else:
            # Weibull-style age dependence (faults/hazard.py): the fleet
            # intensity follows lam(t) = rate * k * (t/horizon)^(k-1),
            # normalized so the expected count over the horizon equals
            # the homogeneous process at the same mtbf.  Sampled by time
            # rescaling: unit-exponential partial sums S_i in transformed
            # time invert through the cumulative hazard
            # H(t) = rate * horizon * (t/horizon)^k.
            k = config.hazard_shape
            total = rate * horizon
            s = rng.expovariate(1.0)
            while s < total:
                t = horizon * (s / total) ** (1.0 / k)
                records.append(
                    FaultRecord(t, mtbf_scope(), repair_duration(), "mtbf")
                )
                s += rng.expovariate(1.0)

    # -- planned maintenance windows (deterministic) ------------------- #
    if config.maintenance_period > 0 and horizon > 0:
        k = 1
        t = config.maintenance_period
        while t <= horizon:
            if flavor == "tpu":
                scope = ("pod", (k - 1) % inner.num_pods)
            elif flavor == "gpu":
                n_nodes = inner.num_switches * inner.nodes_per_switch
                idx = (k - 1) % n_nodes
                scope = ("node", idx // inner.nodes_per_switch,
                         idx % inner.nodes_per_switch)
            else:
                scope = ("chips", max(1, inner.total_chips // 8))
            records.append(
                FaultRecord(t, scope, config.maintenance_duration, "maintenance")
            )
            k += 1
            t = k * config.maintenance_period

    # -- correlated domain outages (host/rack/pod blast radius) -------- #
    if (
        config.domain_mtbf > 0
        and math.isfinite(config.domain_mtbf)
        and horizon > 0
    ):
        domains = getattr(inner, "failure_domains", lambda: [])()
        weights = config.domain_weights
        if weights is not None:
            unknown = set(weights) - {lvl for lvl, _ in domains}
            if unknown and domains:
                raise ValueError(
                    f"domain_weights name levels this cluster has no "
                    f"domains for: {sorted(unknown)}"
                )
            if any(w < 0 for w in weights.values()):
                raise ValueError(
                    f"domain_weights must be >= 0, got {weights}"
                )
            # zero-weighted levels leave the process entirely
            domains = [
                (lvl, scope) for lvl, scope in domains
                if weights.get(lvl, 1.0) > 0.0
            ]
        if domains:
            rng = random.Random(f"{seed}:faults:domain")

            def domain_duration() -> float:
                if math.isinf(config.domain_repair):
                    return math.inf
                if config.domain_repair > 0:
                    return rng.expovariate(1.0 / config.domain_repair)
                return 0.0

            if weights is None:
                # every domain is an independent Poisson process at rate
                # 1/domain_mtbf; the superposition picks uniformly, so
                # host outages dominate in aggregate simply because there
                # are more hosts than racks than pods.  This branch is
                # the historical draw sequence, byte-identical by pin.
                rate = len(domains) / config.domain_mtbf
                t = rng.expovariate(rate)
                while t <= horizon:
                    level, scope = domains[rng.randrange(len(domains))]
                    records.append(FaultRecord(
                        t, scope, domain_duration(), "domain", level=level,
                    ))
                    t += rng.expovariate(rate)
            else:
                # per-level rate weighting (ISSUE 8 satellite): a domain
                # at level L fires at weight(L)/domain_mtbf, so the
                # superposition rate is sum(weights)/domain_mtbf and the
                # pick is weighted by cumulative level weight
                import bisect

                cum: List[float] = []
                acc = 0.0
                for lvl, _ in domains:
                    acc += weights.get(lvl, 1.0)
                    cum.append(acc)
                rate = acc / config.domain_mtbf
                t = rng.expovariate(rate)
                while t <= horizon:
                    idx = bisect.bisect_right(cum, rng.random() * acc)
                    level, scope = domains[min(idx, len(domains) - 1)]
                    records.append(FaultRecord(
                        t, scope, domain_duration(), "domain", level=level,
                    ))
                    t += rng.expovariate(rate)

    # -- straggler chips (degrade, never revoke) ----------------------- #
    if (
        flavor in ("tpu", "gpu")
        and config.straggler_mtbf > 0
        and math.isfinite(config.straggler_mtbf)
        and horizon > 0
    ):
        rng = random.Random(f"{seed}:faults:straggler")
        if flavor == "tpu":
            n_units = inner.total_chips
        else:
            n_units = inner.num_switches * inner.nodes_per_switch
        rate = n_units / config.straggler_mtbf

        def straggler_duration() -> float:
            if math.isinf(config.straggler_repair):
                return math.inf
            if config.straggler_repair > 0:
                return rng.expovariate(1.0 / config.straggler_repair)
            return 0.0

        t = rng.expovariate(rate)
        while t <= horizon:
            if flavor == "tpu":
                scope = (
                    "chip",
                    rng.randrange(inner.num_pods),
                    tuple(rng.randrange(d) for d in inner.dims),
                )
            else:
                scope = (
                    "node",
                    rng.randrange(inner.num_switches),
                    rng.randrange(inner.nodes_per_switch),
                )
            records.append(FaultRecord(
                t, scope, straggler_duration(), "straggler",
                degrade=config.straggler_degrade,
            ))
            t += rng.expovariate(rate)

    # -- DCN-uplink degradation (TPU fleets; slows, never kills) ------- #
    if (
        flavor == "tpu"
        and config.link_mtbf > 0
        and math.isfinite(config.link_mtbf)
        and horizon > 0
    ):
        rng = random.Random(f"{seed}:faults:link")
        rate = inner.num_pods / config.link_mtbf

        def link_duration() -> float:
            if math.isinf(config.link_repair):
                return math.inf
            if config.link_repair > 0:
                return rng.expovariate(1.0 / config.link_repair)
            return 0.0

        t = rng.expovariate(rate)
        while t <= horizon:
            records.append(FaultRecord(
                t, ("link", rng.randrange(inner.num_pods)), link_duration(),
                "link", degrade=config.link_degrade,
            ))
            t += rng.expovariate(rate)

    # -- spot/preemptible revocation ----------------------------------- #
    # spot_mtbf=inf (or <=0) means the spot capacity is never revoked:
    # no records, rather than a ZeroDivisionError out of expovariate
    if (
        config.spot_fraction > 0
        and horizon > 0
        and config.spot_mtbf > 0
        and math.isfinite(config.spot_mtbf)
    ):
        rng = random.Random(f"{seed}:faults:spot")
        units: List[Tuple] = []
        if flavor == "tpu":
            n = max(1, math.ceil(config.spot_fraction * inner.num_pods))
            units = [("pod", p) for p in range(inner.num_pods - n, inner.num_pods)]
        elif flavor == "gpu":
            nodes = [
                (s, n)
                for s in range(inner.num_switches)
                for n in range(inner.nodes_per_switch)
            ]
            k = max(1, math.ceil(config.spot_fraction * len(nodes)))
            units = [("node", s, n) for s, n in nodes[-k:]]
        else:
            units = [("chips", max(1, math.ceil(config.spot_fraction * inner.total_chips)))]
        for scope in units:
            t = rng.expovariate(1.0 / config.spot_mtbf)
            while t <= horizon:
                records.append(FaultRecord(
                    t, scope, config.spot_outage, "spot",
                    warning=config.spot_warning,
                ))
                # a unit cannot be revoked again while already revoked
                t += config.spot_outage + rng.expovariate(1.0 / config.spot_mtbf)

    records.sort(key=lambda r: (r.time, r.kind, repr(r.scope)))
    return records


# --------------------------------------------------------------------- #
# CLI spec parsing:  run --faults mtbf=86400,repair=3600,ckpt=1800

_SPEC_KEYS = {
    "mtbf": ("config", "mtbf"),
    "repair": ("config", "repair"),
    "maintenance": ("config", "maintenance_period"),
    "maintenance_duration": ("config", "maintenance_duration"),
    "spot": ("config", "spot_fraction"),
    "spot_mtbf": ("config", "spot_mtbf"),
    "spot_outage": ("config", "spot_outage"),
    "spot_warning": ("config", "spot_warning"),
    "domain_mtbf": ("config", "domain_mtbf"),
    "domain_repair": ("config", "domain_repair"),
    # per-level domain rate multipliers (ISSUE 8 satellite): the
    # single-knob domain_mtbf form stays untouched when none is given
    "domain_host": ("weight", "host"),
    "domain_rack": ("weight", "rack"),
    "domain_pod": ("weight", "pod"),
    "hazard_shape": ("config", "hazard_shape"),
    "hazard_util": ("config", "hazard_util_weight"),
    "migrate_threshold": ("config", "migrate_threshold"),
    "straggler_mtbf": ("config", "straggler_mtbf"),
    "straggler_repair": ("config", "straggler_repair"),
    "straggler_degrade": ("config", "straggler_degrade"),
    "link_mtbf": ("config", "link_mtbf"),
    "link_repair": ("config", "link_repair"),
    "link_degrade": ("config", "link_degrade"),
    "ckpt": ("recovery", "ckpt_interval"),
    "restore": ("recovery", "restore"),
    "ckpt_write": ("recovery", "ckpt_write"),
}

# Config fields deliberately outside the per-key spec surface, each with
# its one-line justification — the contract linter (GS404, per-key hash
# coverage) refuses a FaultConfig/RecoveryModel field that neither a
# _SPEC_KEYS row reaches nor this allowlist documents: only the spec
# STRING rides the config hash, so an unreachable field would reshape
# replays without ever changing the hash.
_UNSPECCED = {
    "domain_weights": "populated exclusively by the domain_host/"
                      "domain_rack/domain_pod weight keys, which ride "
                      "the spec string themselves",
}


def parse_fault_spec(spec: str):
    """Parse the CLI's ``--faults k=v,...`` spec into a
    ``(FaultConfig, RecoveryModel)`` pair.

    Keys: ``mtbf``, ``repair``, ``maintenance`` (period),
    ``maintenance_duration``, ``spot`` (fraction), ``spot_mtbf``,
    ``spot_outage``, ``spot_warning`` (pre-revoke notice lead time),
    ``domain_mtbf``, ``domain_repair`` (correlated host/rack/pod
    outages), ``domain_host``/``domain_rack``/``domain_pod`` (per-level
    outage-rate multipliers; omitting all keeps the historical uniform
    pick), ``hazard_shape`` (Weibull shape of the MTBF process; 1 =
    memoryless), ``hazard_util`` (effective-age seconds per busy
    chip-second, the runtime wear term), ``migrate_threshold``
    (gang-exposure trigger for proactive checkpoint-and-migrate; inf =
    never), ``straggler_mtbf``, ``straggler_repair``,
    ``straggler_degrade`` (residual chip-rate fraction), ``link_mtbf``,
    ``link_repair``, ``link_degrade`` (residual capacity fraction),
    ``ckpt`` (checkpoint interval), ``restore`` (seconds or ``auto``),
    ``ckpt_write`` (per-checkpoint write cost: seconds, or ``auto`` to
    size it from the model's training state).  Values are seconds unless
    noted; ``inf`` is accepted.
    """
    from gpuschedule_tpu.faults.recovery import RecoveryModel

    config = FaultConfig()
    recovery = RecoveryModel()
    for pair in spec.split(","):
        pair = pair.strip()
        if not pair:
            continue
        key, sep, raw = pair.partition("=")
        key = key.strip().replace("-", "_")
        if not sep or key not in _SPEC_KEYS:
            raise ValueError(
                f"bad --faults entry {pair!r}; known keys: {sorted(_SPEC_KEYS)}"
            )
        target, attr = _SPEC_KEYS[key]
        if key in ("restore", "ckpt_write") and raw.strip() == "auto":
            value: object = "auto"
        else:
            value = float(raw)
        if target == "weight":
            if config.domain_weights is None:
                config.domain_weights = {}
            config.domain_weights[attr] = float(value)
        else:
            setattr(config if target == "config" else recovery, attr, value)
    if not 0.0 <= config.straggler_degrade <= 1.0:
        raise ValueError(
            f"straggler_degrade is the residual chip-rate FRACTION in "
            f"[0, 1], got {config.straggler_degrade}"
        )
    if config.spot_warning < 0.0:
        raise ValueError(
            f"spot_warning is a lead time in seconds >= 0, got "
            f"{config.spot_warning}"
        )
    if recovery.ckpt_write != "auto" and float(recovery.ckpt_write) < 0.0:
        raise ValueError(
            f"ckpt_write is seconds per checkpoint write >= 0 (or "
            f"'auto'), got {recovery.ckpt_write}"
        )
    if config.hazard_shape <= 0.0:
        raise ValueError(
            f"hazard_shape is a Weibull shape > 0 (1 = memoryless), got "
            f"{config.hazard_shape}"
        )
    if config.hazard_util_weight < 0.0:
        raise ValueError(
            f"hazard_util is effective-age seconds per busy chip-second "
            f">= 0, got {config.hazard_util_weight}"
        )
    if config.migrate_threshold <= 0.0:
        raise ValueError(
            f"migrate_threshold is a gang-exposure trigger > 0 (inf = "
            f"never), got {config.migrate_threshold}"
        )
    if config.domain_weights is not None and any(
        w < 0 for w in config.domain_weights.values()
    ):
        raise ValueError(
            f"domain level weights must be >= 0, got {config.domain_weights}"
        )
    if not 0.0 <= config.link_degrade <= 1.0:
        # a fraction, not seconds: an out-of-range value would be clamped
        # downstream (net/), silently turning every link fault into a
        # no-op while the counters still tick
        raise ValueError(
            f"link_degrade is the residual capacity FRACTION in [0, 1], "
            f"got {config.link_degrade}"
        )
    return config, recovery
