"""MTBF sweep harness: goodput-vs-failure-rate across the policy suite.

The robustness question the fault subsystem exists to answer is "which
policy degrades most gracefully as hardware gets flakier?".  This module
runs it as a grid: for each (policy config, MTBF) cell, replay the same
seeded Philly-like trace on a fresh cluster with a freshly generated
fault schedule, and report the goodput decomposition (useful / lost /
restart-overhead chip-seconds) next to the usual JCT/makespan headline
numbers.  ``tools/fault_sweep.py`` is the CLI wrapper that writes the
JSON artifact; the functions here are importable so the pytest smoke can
run one tiny cell end-to-end.

``POLICY_CONFIGS`` is the eight-point policy suite the sweep covers: the
six registered policies plus the two variants that change their failure
story (FIFO with backfill — head-of-line blocking interacts badly with
requeued victims — and SRTF with model-derived restart costs).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from gpuschedule_tpu.cluster.tpu import TpuCluster
from gpuschedule_tpu.faults.recovery import FaultPlan, RecoveryModel
from gpuschedule_tpu.faults.schedule import (
    FaultConfig,
    fault_horizon,
    generate_fault_schedule,
    scope_capacity,
)

# Fault kinds that take capacity out of the pool (availability accounting);
# link and straggler records only degrade, they never remove chips.
_CAPACITY_KINDS = ("mtbf", "maintenance", "spot", "domain")
from gpuschedule_tpu.obs.fleet import (
    task_profiler as _task_profiler,
    task_span as _task_span,
)
from gpuschedule_tpu.policies import make_policy
from gpuschedule_tpu.sim import Simulator
from gpuschedule_tpu.sim.metrics import MetricsLog
from gpuschedule_tpu.sim.philly import generate_philly_like_trace

# name -> (registry policy, constructor kwargs): the eight-policy suite.
POLICY_CONFIGS: Dict[str, Tuple[str, dict]] = {
    "fifo": ("fifo", {}),
    "fifo-backfill": ("fifo", {"backfill": True}),
    "srtf": ("srtf", {}),
    "srtf-ckpt": ("srtf", {"restart_overhead": "auto"}),
    "dlas": ("dlas", {}),
    "gandiva": ("gandiva", {}),
    "optimus": ("optimus", {}),
    "themis": ("themis", {}),
}

# Default sweep grid: one-failure-a-month-per-chip down to one-an-hour,
# plus inf (the fault-free control arm).
DEFAULT_MTBFS: Tuple[float, ...] = (
    math.inf, 30 * 86400.0, 7 * 86400.0, 86400.0, 6 * 3600.0, 3600.0
)


def jsonable(obj):
    """Strict-JSON projection: non-finite floats become the strings
    "inf"/"-inf"/"nan" (json.dumps would otherwise emit the non-standard
    ``Infinity`` token, which jq / JSON.parse / any spec-compliant reader
    rejects — and the inf control arm is on the DEFAULT grid)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return "nan" if math.isnan(obj) else ("inf" if obj > 0 else "-inf")
    if isinstance(obj, dict):
        return {k: jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    return obj


def availability_summary(cluster, records, end_time: float) -> dict:
    """Availability and MTTR columns for one cell, from the fault
    schedule the replay actually saw (records past ``end_time`` never
    fired).

    - ``availability``: 1 - (downed chip-seconds / total chip-seconds),
      summing each capacity-outage record's scope size times its
      horizon-capped duration.  Overlapping outages on the same chips
      are double-counted (the per-record sum is an upper bound on
      downtime, so this is a lower bound on availability — exact
      whenever outages don't overlap).
    - ``mttr_s``: mean repair time over the finite-duration capacity
      outages that fired (``nan`` when none did — the fault-free control
      arm; the JSON writers map it through the "inf"/"nan" string
      convention)."""
    downtime = 0.0
    repairs: List[float] = []
    for rec in records:
        if rec.time > end_time or rec.kind not in _CAPACITY_KINDS:
            continue
        span = max(0.0, min(rec.duration, end_time - rec.time))
        downtime += scope_capacity(cluster, rec.scope) * span
        if math.isfinite(rec.duration):
            repairs.append(rec.duration)
    cap = cluster.total_chips * end_time
    return {
        "availability": (
            max(0.0, 1.0 - downtime / cap) if cap > 0 else 1.0
        ),
        "mttr_s": sum(repairs) / len(repairs) if repairs else float("nan"),
    }


def run_cell(
    policy_key: str,
    *,
    mtbf: float,
    repair: float = 3600.0,
    ckpt: float = 1800.0,
    restore="auto",
    ckpt_write=0.0,
    num_jobs: int = 200,
    seed: int = 0,
    dims: Sequence[int] = (8, 8),
    num_pods: int = 1,
    max_time: Optional[float] = None,
    events_path=None,
    attribution: bool = False,
    sample_interval: Optional[float] = None,
    domain_mtbf: float = math.inf,
    domain_repair: float = 2 * 3600.0,
    domain_weights: Optional[Dict[str, float]] = None,
    hazard_shape: float = 1.0,
    hazard_util_weight: float = 0.0,
    migrate_threshold: float = math.inf,
    straggler_mtbf: float = math.inf,
    straggler_repair: float = 3600.0,
    straggler_degrade: float = 0.5,
    spot_fraction: float = 0.0,
    spot_mtbf: float = 4 * 3600.0,
    spot_outage: float = 1800.0,
    spot_warning: float = 0.0,
) -> dict:
    """Run one (policy, MTBF) cell on a fresh cluster + trace + schedule.

    Jobs are regenerated per cell (the engine mutates them), the fault
    schedule is regenerated from the same seed (seed-split rule in
    :mod:`gpuschedule_tpu.faults.schedule`), so any two calls with the
    same arguments produce identical results.

    ``events_path`` streams the cell's transition log there as JSONL,
    opened with a schema header (the cell's identity; the config hash
    covers everything but the policy, so two cells at the same seed are
    `compare`-compatible) — the CLI ``faults --events DIR`` path.

    ``attribution`` / ``sample_interval`` arm the causal-attribution and
    cluster-sampling layers (ISSUE 5): the captured stream then carries
    blame/sample records and the cell reports ``delay_by_cause``, so a
    chaos sweep answers not just *how much* goodput each policy lost but
    *where its jobs' time went* — defaults keep every existing cell
    byte-identical.

    ISSUE 6 passthrough: ``domain_*`` (correlated host/rack/pod
    outages), ``straggler_*`` (slow chips), ``spot_*`` (+ the
    ``spot_warning`` pre-revoke window), and ``ckpt_write`` (priced
    checkpoint writes) — all defaulting off, so pre-existing grids stay
    byte-identical.  Every cell additionally reports ``availability``
    and ``mttr_s`` next to the goodput decomposition.

    ISSUE 8 passthrough (same default-off, hash-gated contract):
    ``domain_weights`` (per-level outage-rate multipliers),
    ``hazard_shape`` / ``hazard_util_weight`` (Weibull-aged,
    wear-scored failure hazard), and ``migrate_threshold`` (proactive
    checkpoint-and-migrate trigger; arms ``plan.hazard``).
    """
    from gpuschedule_tpu.faults.hazard import hazard_config

    name, kwargs = POLICY_CONFIGS[policy_key]
    # ISSUE 16: under a fleet task harness (pooled or serial sweep with
    # tracing armed) the cell's build/replay phases land as worker-side
    # spans and the engine runs a per-cell PhaseProfiler; all three hooks
    # are one-global-read no-ops disarmed, so bare cells stay identical
    with _task_span("build", cat="sweep", policy=policy_key):
        cluster = TpuCluster("v5e", dims=tuple(dims), num_pods=num_pods)
        jobs = generate_philly_like_trace(num_jobs, seed=seed)
        horizon = max_time if max_time is not None else fault_horizon(jobs)
        fconfig = FaultConfig(
            mtbf=mtbf, repair=repair,
            domain_mtbf=domain_mtbf, domain_repair=domain_repair,
            domain_weights=domain_weights,
            hazard_shape=hazard_shape,
            hazard_util_weight=hazard_util_weight,
            migrate_threshold=migrate_threshold,
            straggler_mtbf=straggler_mtbf,
            straggler_repair=straggler_repair,
            straggler_degrade=straggler_degrade,
            spot_fraction=spot_fraction, spot_mtbf=spot_mtbf,
            spot_outage=spot_outage, spot_warning=spot_warning,
        )
        plan = FaultPlan(
            records=generate_fault_schedule(
                cluster, fconfig, horizon=horizon, seed=seed,
            ),
            recovery=RecoveryModel(
                ckpt_interval=ckpt, restore=restore, ckpt_write=ckpt_write,
            ),
            hazard=hazard_config(fconfig),
        )
    metrics = MetricsLog(attribution=attribution)
    if events_path is not None:
        from gpuschedule_tpu.obs import config_hash

        # new-knob keys enter the hash only when their process is armed:
        # knob-off cells keep their PR-5 config hashes (and run_ids, and
        # events headers) byte for byte
        extra_cfg: dict = {}
        # arming predicates mirror generate_fault_schedule's exactly: a
        # knob value that generates zero records must not perturb the hash
        if domain_mtbf > 0 and math.isfinite(domain_mtbf):
            extra_cfg["domain"] = [domain_mtbf, domain_repair]
            if domain_weights:
                extra_cfg["domain_weights"] = dict(sorted(
                    domain_weights.items()
                ))
        if plan.hazard is not None:
            extra_cfg["hazard"] = [
                hazard_shape, hazard_util_weight, migrate_threshold,
            ]
        if straggler_mtbf > 0 and math.isfinite(straggler_mtbf):
            extra_cfg["straggler"] = [
                straggler_mtbf, straggler_repair, straggler_degrade
            ]
        if spot_fraction > 0:
            extra_cfg["spot"] = [
                spot_fraction, spot_mtbf, spot_outage, spot_warning
            ]
        if ckpt_write == "auto" or (
            isinstance(ckpt_write, (int, float)) and ckpt_write
        ):
            extra_cfg["ckpt_write"] = ckpt_write
        chash = config_hash({
            "cluster": "tpu-v5e", "dims": list(dims), "num_pods": num_pods,
            "trace": f"philly-like:{num_jobs}", "seed": seed,
            "mtbf": mtbf, "repair": repair, "ckpt": ckpt,
            "restore": restore, "max_time": max_time, **extra_cfg,
        })
        metrics = MetricsLog(events_sink=events_path, run_meta={
            "run_id": f"{policy_key}-s{seed}-{chash}",
            "seed": seed, "policy": policy_key, "config_hash": chash,
        }, attribution=attribution)
    with metrics:  # engine exceptions still flush the stream
        with _task_span("replay", cat="sweep", policy=policy_key,
                        mtbf=mtbf, seed=seed):
            res = Simulator(
                cluster, make_policy(name, **kwargs), jobs,
                metrics=metrics,
                faults=plan,
                max_time=max_time if max_time is not None else math.inf,
                sample_interval=sample_interval,
                profiler=_task_profiler(),
            ).run()
    cell = {
        "policy": policy_key,
        "mtbf_s": mtbf,
        "avg_jct": res.avg_jct,
        "makespan": res.makespan,
        "num_finished": res.num_finished,
        "num_unfinished": res.num_unfinished,
        "faults": int(res.counters.get("faults", 0)),
        "revocations": int(res.counters.get("fault_revocations", 0)),
        "goodput": dict(res.goodput),
        # availability / MTTR summary columns (ISSUE 6 satellite): what
        # fraction of fleet chip-time stayed in service, and how fast
        # outages healed, next to the goodput they cost
        **availability_summary(cluster, plan.records, res.end_time),
    }
    if res.delay_by_cause:
        cell["delay_by_cause"] = dict(res.delay_by_cause)
    if events_path is not None:
        cell["events"] = str(events_path)
    return cell


def grid_cells(
    keys: Sequence[str],
    points: Sequence,
    run_one,
    *,
    workers: int = 1,
    max_retries: int = 2,
    backoff_s: float = 1.0,
    retry_log: Optional[List[dict]] = None,
    fleet=None,
) -> Dict[str, List[dict]]:
    """Run a (policy x grid-point) matrix of independent seeded cells,
    serially or process-parallel, reassembling results in deterministic
    grid order either way (ISSUE 7: each cell regenerates its own trace /
    cluster / schedule from the seed, so cells are embarrassingly
    parallel and the parallel artifact is byte-identical to the serial
    one).  ``run_one(key, point)`` must be picklable (module-level) for
    ``workers > 1``.

    Crash resilience (ISSUE 8 satellite): a cell whose worker crashed or
    was killed (OOM-killer, a hard ``os._exit``) is retried up to
    ``max_retries`` times with exponential backoff
    (``backoff_s * 2^round``) before the grid fails; only the failed
    cells re-run, and results still reassemble in grid order, so a
    transiently-killed worker cannot perturb the artifact.  The serial
    path retries raising cells the same way.  ``retry_log`` (when given)
    collects one ``{"cell": [key, index], "round": n}`` record per
    retried cell — ``tools/fault_chaos.py`` reports them.

    ISSUE 12: the parallel path rides the shared persistent
    :class:`~gpuschedule_tpu.sim.pool.WorkerPool` — one long-lived set
    of warm workers for the whole grid, a crash respawning exactly the
    dead worker instead of a fresh pool per retry round.  Cells are
    independent seeded replays either way, so the artifact stays
    byte-identical to the serial one.

    ``fleet`` (a :class:`gpuschedule_tpu.obs.fleet.FleetCollector`,
    ISSUE 16) arms cross-process tracing: pooled cells ship a
    trace-context envelope and return spans / counters / engine-phase
    profiles alongside their results; serial cells run the identical
    harness in-process, so the federated telemetry is comparable across
    modes.  The pool's lifecycle counters land on ``fleet.registry``.
    Cell *results* are bytewise unaffected either way — telemetry
    travels out of band, and a failed attempt's partial telemetry never
    reaches the collector (it only rides a successful return)."""
    import time

    def note_retries(cells, rnd: int) -> None:
        if retry_log is not None:
            for key, i in cells:
                retry_log.append({"cell": [key, i], "round": rnd})

    if workers <= 1:
        out: Dict[str, List[dict]] = {}
        for k, key in enumerate(keys):
            row = []
            for i, pt in enumerate(points):
                for attempt in range(max_retries + 1):
                    try:
                        if fleet is None:
                            row.append(run_one(key, pt))
                        else:
                            # the serial half of the fleet contract: same
                            # harness, task-index key = grid-flat index
                            row.append(fleet.run_local(
                                run_one, k * len(points) + i, (key, pt),
                            ))
                        break
                    except Exception:
                        if attempt == max_retries:
                            raise
                        note_retries([(key, i)], attempt + 1)
                        time.sleep(backoff_s * (2 ** attempt))
            out[key] = row
        return out
    from gpuschedule_tpu.sim.pool import WorkerPool

    cells = [(key, i) for key in keys for i in range(len(points))]
    tasks = [(key, points[i]) for key, i in cells]

    def on_retry(idx: int, attempt: int) -> None:
        note_retries([cells[idx]], attempt)

    with WorkerPool(
        workers, max_retries=max_retries, backoff_s=backoff_s,
        on_retry=on_retry,
        registry=fleet.registry if fleet is not None else None,
    ) as pool:
        if fleet is None:
            flat = pool.map(run_one, tasks)
        else:
            with fleet.span("dispatch", tasks=len(tasks)):
                flat = pool.map(run_one, tasks, fleet=fleet)
    results = dict(zip(cells, flat))
    return {
        key: [results[(key, i)] for i in range(len(points))] for key in keys
    }


def _mtbf_cell(key: str, mtbf: float, cell_kwargs: dict) -> dict:
    """Module-level cell thunk (picklable for the process pool)."""
    return run_cell(key, mtbf=mtbf, **cell_kwargs)


def sweep(
    mtbfs: Iterable[float] = DEFAULT_MTBFS,
    policies: Optional[Iterable[str]] = None,
    *,
    workers: int = 1,
    fleet=None,
    **cell_kwargs,
) -> dict:
    """The full grid as one JSON-ready artifact:
    ``{"mtbf_s": [...], "policies": {name: [cell, ...]}}`` with each
    policy's cells ordered like ``mtbf_s``.

    ``workers`` > 1 runs the cells across a process pool (each cell is an
    isolated seeded replay); results come back in grid order, so the
    artifact is byte-identical to the serial one.  ``fleet`` arms
    ISSUE 16 cross-process tracing (see :func:`grid_cells`) — the
    artifact itself is unchanged; the telemetry rides the collector."""
    mtbfs = list(mtbfs)
    keys = list(policies) if policies is not None else list(POLICY_CONFIGS)
    unknown = [k for k in keys if k not in POLICY_CONFIGS]
    if unknown:
        raise ValueError(
            f"unknown policy configs {unknown}; known: {sorted(POLICY_CONFIGS)}"
        )
    if workers > 1 and cell_kwargs.get("events_path") is not None:
        raise ValueError(
            "workers > 1 cannot share one events_path; capture streams "
            "per-cell (cli `faults --events DIR`) or run serially"
        )
    from functools import partial

    out = grid_cells(
        keys, mtbfs, partial(_mtbf_cell, cell_kwargs=cell_kwargs),
        workers=workers, fleet=fleet,
    )
    return {"mtbf_s": mtbfs, "policies": out}
