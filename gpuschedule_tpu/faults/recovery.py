"""Fault recovery model: checkpoint rollback + restore cost.

What a fault costs a victim job is decided here, not in the engine: the
engine mechanically applies whatever this model says.  The model is the
standard periodic-checkpoint one (the Philly clusters checkpointed
long-running jobs; Gandiva's suspend/resume measurements are the cost
anchor this repo already models in :mod:`gpuschedule_tpu.sim.overhead`):

- **lost progress**: a job checkpoints every ``ckpt_interval``
  reference-speed seconds of work (per-job ``Job.ckpt_interval`` wins over
  the model default), so a revocation rolls ``executed_work`` back to the
  last checkpoint multiple — ``executed_work % interval`` work-seconds are
  forfeited.  ``interval=inf`` means "never checkpoints" (all progress
  lost); ``interval<=0`` means continuous checkpointing (nothing lost).
- **restore cost**: seconds of ``overhead_remaining`` charged at
  revocation time and burned (at wall-clock rate, before any new work
  accrues) once the job next runs — the existing suspend/resume overhead
  path.  ``restore="auto"`` derives the cost from the job's model size and
  gang via :func:`gpuschedule_tpu.sim.overhead.resolve_overhead`; a float
  is a flat cost in seconds.
- **checkpoint-write cost** (priced recovery, ISSUE 6): the periodic
  checkpoints themselves are no longer free.  ``ckpt_write`` is the
  seconds one write takes (``"auto"`` sizes it from the model's training
  state streaming out through the slice's hosts,
  :func:`gpuschedule_tpu.sim.overhead.ckpt_write_seconds`; 0 keeps the
  historical free-write model).  The engine folds it into
  ``Job.advance`` as the write-time fraction of every productive
  interval — charged to the ``overhead`` leg of the goodput and
  attribution decompositions — so a short ``ckpt_interval`` now trades
  steady overhead against less lost work per revocation.
- **emergency checkpoints**: a spot revocation announced
  ``spot_warning`` seconds ahead lets a victim checkpoint *at the
  warning* when the window covers the write cost: the engine charges the
  write as overhead inside the window and the rollback floor rises to
  the warned watermark (``Job.ckpt_protected``), so only the window's
  tail of work is lost instead of a full checkpoint interval.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Union

from gpuschedule_tpu.faults.hazard import HazardConfig, hazard_config
from gpuschedule_tpu.faults.schedule import (
    FaultConfig,
    FaultRecord,
    generate_fault_schedule,
)
from gpuschedule_tpu.sim.overhead import (
    ckpt_write_seconds as _ckpt_write_seconds,
    cluster_generation,
    resolve_overhead,
)


@dataclass
class RecoveryModel:
    """How a victim job recovers from a revocation — and what staying
    recoverable costs while nothing is failing (the checkpoint-write
    price)."""

    ckpt_interval: float = 1800.0           # work-seconds between checkpoints
    restore: Union[float, str] = "auto"     # seconds, or "auto" (sim/overhead.py)
    ckpt_write: Union[float, str] = 0.0     # seconds per periodic checkpoint
                                            # write ("auto" sizes it from model
                                            # state bytes; 0 = free, the PR-2
                                            # model — the regression default)

    def checkpoint_interval(self, job) -> float:
        ji = getattr(job, "ckpt_interval", None)
        return self.ckpt_interval if ji is None else float(ji)

    def writes_cost(self) -> bool:
        """True when checkpoint writes are priced (``ckpt_write`` armed)."""
        return self.ckpt_write == "auto" or float(self.ckpt_write) > 0.0

    def ckpt_write_seconds(self, job, cluster) -> float:
        """Seconds one checkpoint write (periodic or emergency) takes for
        this job: the flat knob, or the modeled state-streaming time."""
        if self.ckpt_write == "auto":
            return _ckpt_write_seconds(
                job.model_name,
                max(1, job.allocated_chips or job.num_chips),
                generation=cluster_generation(cluster),
            )
        return float(self.ckpt_write)

    def lost_progress(self, job, *, use_emergency: bool = True) -> float:
        """Reference-speed seconds of work rolled back by one revocation.

        The rollback floor is the newest of the periodic-checkpoint
        multiple and the emergency watermark a warned spot revocation
        wrote (``Job.ckpt_protected``); ``use_emergency=False`` reports
        the unwarned loss, which is how the engine tells warned from
        unwarned revocations in the event stream."""
        interval = self.checkpoint_interval(job)
        if interval <= 0.0:
            return 0.0
        if math.isinf(interval):
            lost = job.executed_work
        else:
            lost = math.fmod(job.executed_work, interval)
        if use_emergency:
            protected = getattr(job, "ckpt_protected", None)
            if protected is not None:
                lost = min(
                    lost,
                    job.executed_work - min(protected, job.executed_work),
                )
        return lost

    def restore_overhead(self, job, cluster) -> float:
        """Seconds of modeled restart cost charged to one victim."""
        return resolve_overhead(self.restore, job, cluster)


@dataclass
class FaultPlan:
    """Everything the engine needs to run a faulty replay: the (already
    generated, time-sorted) fault schedule plus the recovery model applied
    to every victim.  An empty ``records`` list is a valid plan — the
    fault path is armed but never fires (the ``mtbf=inf`` case).

    ``hazard`` (ISSUE 8) is the armed hazard knobs when any is set: the
    engine builds a runtime :class:`~gpuschedule_tpu.faults.hazard.
    HazardModel` from it, binds it to the cluster (so
    ``cluster.hazard_score`` answers), and arms the proactive
    checkpoint-and-migrate trigger.  None — the default — keeps the
    hazard machinery entirely out of the run."""

    records: List[FaultRecord] = field(default_factory=list)
    recovery: RecoveryModel = field(default_factory=RecoveryModel)
    hazard: Optional["HazardConfig"] = None


def make_fault_plan(
    cluster,
    config: Optional[FaultConfig] = None,
    recovery: Optional[RecoveryModel] = None,
    *,
    horizon: float,
    seed: int = 0,
) -> FaultPlan:
    """Convenience constructor: generate the schedule and bundle it with a
    recovery model (both defaulted) into one plan.  Hazard knobs on the
    config (``hazard_shape`` / ``hazard_util_weight`` /
    ``migrate_threshold``) ride along as ``plan.hazard``."""
    config = config or FaultConfig()
    return FaultPlan(
        records=generate_fault_schedule(
            cluster, config, horizon=horizon, seed=seed
        ),
        recovery=recovery or RecoveryModel(),
        hazard=hazard_config(config),
    )
