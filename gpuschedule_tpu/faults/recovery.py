"""Fault recovery model: checkpoint rollback + restore cost.

What a fault costs a victim job is decided here, not in the engine: the
engine mechanically applies whatever this model says.  The model is the
standard periodic-checkpoint one (the Philly clusters checkpointed
long-running jobs; Gandiva's suspend/resume measurements are the cost
anchor this repo already models in :mod:`gpuschedule_tpu.sim.overhead`):

- **lost progress**: a job checkpoints every ``ckpt_interval``
  reference-speed seconds of work (per-job ``Job.ckpt_interval`` wins over
  the model default), so a revocation rolls ``executed_work`` back to the
  last checkpoint multiple — ``executed_work % interval`` work-seconds are
  forfeited.  ``interval=inf`` means "never checkpoints" (all progress
  lost); ``interval<=0`` means continuous checkpointing (nothing lost).
- **restore cost**: seconds of ``overhead_remaining`` charged at
  revocation time and burned (at wall-clock rate, before any new work
  accrues) once the job next runs — the existing suspend/resume overhead
  path.  ``restore="auto"`` derives the cost from the job's model size and
  gang via :func:`gpuschedule_tpu.sim.overhead.resolve_overhead`; a float
  is a flat cost in seconds.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Union

from gpuschedule_tpu.faults.schedule import (
    FaultConfig,
    FaultRecord,
    generate_fault_schedule,
)
from gpuschedule_tpu.sim.overhead import resolve_overhead


@dataclass
class RecoveryModel:
    """How a victim job recovers from a revocation."""

    ckpt_interval: float = 1800.0           # work-seconds between checkpoints
    restore: Union[float, str] = "auto"     # seconds, or "auto" (sim/overhead.py)

    def checkpoint_interval(self, job) -> float:
        ji = getattr(job, "ckpt_interval", None)
        return self.ckpt_interval if ji is None else float(ji)

    def lost_progress(self, job) -> float:
        """Reference-speed seconds of work rolled back by one revocation."""
        interval = self.checkpoint_interval(job)
        if interval <= 0.0:
            return 0.0
        if math.isinf(interval):
            return job.executed_work
        return math.fmod(job.executed_work, interval)

    def restore_overhead(self, job, cluster) -> float:
        """Seconds of modeled restart cost charged to one victim."""
        return resolve_overhead(self.restore, job, cluster)


@dataclass
class FaultPlan:
    """Everything the engine needs to run a faulty replay: the (already
    generated, time-sorted) fault schedule plus the recovery model applied
    to every victim.  An empty ``records`` list is a valid plan — the
    fault path is armed but never fires (the ``mtbf=inf`` case)."""

    records: List[FaultRecord] = field(default_factory=list)
    recovery: RecoveryModel = field(default_factory=RecoveryModel)


def make_fault_plan(
    cluster,
    config: Optional[FaultConfig] = None,
    recovery: Optional[RecoveryModel] = None,
    *,
    horizon: float,
    seed: int = 0,
) -> FaultPlan:
    """Convenience constructor: generate the schedule and bundle it with a
    recovery model (both defaulted) into one plan."""
    return FaultPlan(
        records=generate_fault_schedule(
            cluster, config or FaultConfig(), horizon=horizon, seed=seed
        ),
        recovery=recovery or RecoveryModel(),
    )
