"""Fault injection & recovery (ISSUE 2 tentpole).

A new axis of the simulation: hardware breaks.  The package splits into

- :mod:`gpuschedule_tpu.faults.schedule` — deterministic seeded fault-
  schedule generators (per-chip MTBF exponential processes, planned
  maintenance windows, spot/preemptible revocation) emitting
  ``FaultRecord(time, scope, duration, kind)`` records, plus the CLI
  ``--faults`` spec parser and the seed-split rule shared with trace
  synthesis;
- :mod:`gpuschedule_tpu.faults.recovery` — the victim recovery model
  (checkpoint-interval rollback + restore cost) and the ``FaultPlan``
  bundle the engine consumes;
- :mod:`gpuschedule_tpu.faults.sweep` — the MTBF x policy robustness
  grid behind ``tools/fault_sweep.py`` and the CLI ``faults`` demo.

The engine side lives in :mod:`gpuschedule_tpu.sim.engine` (``_FAULT`` /
``_REPAIR`` event kinds); the cluster side is the health mask each
flavor implements (``mark_unhealthy`` / ``repair`` / ``unhealthy_chips``
in :mod:`gpuschedule_tpu.cluster`).  Like the sim core, this package is
deliberately JAX-free.
"""

from gpuschedule_tpu.faults.hazard import HazardConfig, HazardModel, hazard_config
from gpuschedule_tpu.faults.recovery import FaultPlan, RecoveryModel, make_fault_plan
from gpuschedule_tpu.faults.schedule import (
    FaultConfig,
    FaultRecord,
    fault_horizon,
    generate_fault_schedule,
    parse_fault_spec,
)

__all__ = [
    "FaultConfig",
    "FaultRecord",
    "FaultPlan",
    "RecoveryModel",
    "HazardConfig",
    "HazardModel",
    "fault_horizon",
    "generate_fault_schedule",
    "hazard_config",
    "make_fault_plan",
    "parse_fault_spec",
]
