"""Age- and utilization-dependent failure hazard (ISSUE 8 tentpole).

PR 6 made the fleet *fail* realistically; this module makes the failure
rate itself realistic — and, more importantly, makes it a **signal**
consumers can react to instead of pure damage:

- **Age dependence** (generation-time): with ``hazard_shape != 1`` the
  per-chip MTBF process in :mod:`gpuschedule_tpu.faults.schedule` stops
  being memoryless.  The fleet failure intensity follows a Weibull-style
  power law in replay time, sampled by the classic time-rescaling
  construction (draw unit-exponential arrivals in transformed time and
  invert the cumulative hazard), normalized so the *expected* failure
  count over the horizon matches the homogeneous process at the same
  ``mtbf`` — the knob keeps meaning "mean failures per chip over the
  replay", only their clustering in time changes.  ``shape > 1`` is
  wear-out (failures pile up late), ``shape < 1`` infant mortality.
- **Utilization dependence** (run-time): hardware that works harder ages
  faster.  :class:`HazardModel` integrates per-pod **wear** (busy
  chip-seconds, observed from the cluster's occupancy counters at event-
  batch granularity) and folds it into an *effective age*
  ``A = now + util_weight * wear_per_chip``, so two pods at the same
  wall-clock age score differently when one has been loaded and the
  other idle.  The fault *schedule* cannot depend on runtime utilization
  (it is generated up front, before the replay runs — the deterministic
  seeded-schedule contract); utilization dependence therefore lives
  entirely in the runtime **score** that placement and proactive
  migration consume.

Consumers read the signal as ``cluster.hazard_score(scope)`` (bound via
``cluster.bind_hazard``; 0.0 when no model is armed): the expected
failure arrivals per hour over the scope's chips at their effective age,
plus the flavor's own degrade-mask penalty for known-slow chips (each
straggler chip adds its lost rate fraction — a degraded chip is the most
concrete hazard evidence there is).  The ``health`` placement scheme
orders pods by it, the ``contention`` scheme discounts residual
bandwidth by it, and the engine's proactive checkpoint-and-migrate
trigger (``migrate_threshold``) compares a running gang's combined
straggler + hazard exposure against it.

Deterministic, pure Python, jax-free (sim-core rule).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass(frozen=True)
class HazardConfig:
    """The armed subset of FaultConfig's hazard knobs (what the engine
    needs to build a :class:`HazardModel`; rides ``FaultPlan.hazard``).

    ``life`` is the Weibull characteristic life — the ``mtbf`` knob, so
    one number governs both how often chips fail and how fast they age.
    ``migrate_threshold`` arms the engine's proactive checkpoint-and-
    migrate offer: a running gang whose exposure (lost straggler rate
    plus relative hazard heat) reaches it is offered to
    ``Policy.on_hazard`` (inf = never, the default)."""

    shape: float = 1.0
    util_weight: float = 0.0
    migrate_threshold: float = math.inf
    life: float = math.inf

    @property
    def armed(self) -> bool:
        return (
            self.shape != 1.0
            or self.util_weight > 0.0
            or math.isfinite(self.migrate_threshold)
        )


def hazard_config(config) -> Optional["HazardConfig"]:
    """The :class:`HazardConfig` a FaultConfig's knobs describe, or None
    when every hazard knob sits at its default (the knob-off path: no
    model is built, no wear is tracked, nothing changes)."""
    hc = HazardConfig(
        shape=getattr(config, "hazard_shape", 1.0),
        util_weight=getattr(config, "hazard_util_weight", 0.0),
        migrate_threshold=getattr(config, "migrate_threshold", math.inf),
        life=getattr(config, "mtbf", math.inf),
    )
    return hc if hc.armed else None


class HazardModel:
    """Runtime hazard scoring over one cluster's topology.

    The engine constructs one per run (when the fault plan arms any
    hazard knob), binds it to the cluster (``cluster.bind_hazard``), and
    calls :meth:`observe` once per event batch — wear integrates at
    batch granularity, which is exact while occupancy is constant
    between batches (it is: every occupancy change is itself a batch).
    Scores are a heuristic *signal*, deliberately outside the bit-exact
    accounting closures: they steer placement and migration, they never
    enter the goodput/attribution arithmetic.
    """

    def __init__(self, config: HazardConfig, cluster):
        self.config = config
        inner = getattr(cluster, "inner", cluster)
        # per-pod wear for torus fleets (placement steers pods); one
        # fleet-wide bucket for flavors without pod identity
        self._num_pods = int(getattr(inner, "num_pods", 0) or 0)
        self._pod_chips = int(getattr(inner, "pod_chips", 0) or 0)
        self._total_chips = int(getattr(inner, "total_chips", 0) or 0)
        self.wear: Dict[int, float] = {p: 0.0 for p in range(self._num_pods)}
        self._wear_total = 0.0
        self._last_t = 0.0
        self.now = 0.0

    # ------------------------------------------------------------------ #
    # wear integration (utilization dependence)

    def observe(self, now: float, cluster) -> None:
        """Integrate busy chip-seconds up to ``now`` from the cluster's
        O(1) occupancy counters.  Called by the engine before each event
        batch mutates occupancy, so the integral is exact piecewise."""
        dt = now - self._last_t
        if dt > 0.0:
            if self._num_pods:
                wear = self.wear
                for p in range(self._num_pods):
                    busy = cluster.pod_used_chips(p) * dt
                    wear[p] += busy
                    self._wear_total += busy
            else:
                self._wear_total += cluster.used_chips * dt
        self._last_t = now
        self.now = now

    # ------------------------------------------------------------------ #
    # scoring

    def _rate(self, effective_age: float) -> float:
        """Weibull hazard rate per chip at ``effective_age``:
        ``(k / life) * (A / life)^(k-1)`` — constant ``1/life`` at the
        memoryless shape of 1, rising with age for wear-out shapes.
        0.0 when ``life`` is infinite (no MTBF process armed).

        Calibration caveat: at shape 1 this is exactly the scheduled
        per-chip intensity; at other shapes the *schedule* normalizes
        its power law to the replay horizon (same expected count as the
        memoryless process) while this score uses ``life`` as the
        characteristic scale — the scale the wear-inflated effective age
        lives on.  Ratios between scopes (what placement and the
        proactive trigger consume) agree with the scheduled process;
        absolute magnitudes at shape != 1 are a steering signal, not the
        scheduled failures/hour (docs/faults.md omissions)."""
        life = self.config.life
        if not math.isfinite(life) or life <= 0.0:
            return 0.0
        k = self.config.shape
        if k == 1.0:
            return 1.0 / life
        a = max(0.0, effective_age) / life
        if a == 0.0:
            # k < 1 has an infinite hazard at age 0 (infant mortality);
            # report the rate one second in rather than inf
            a = 1.0 / life
        return (k / life) * a ** (k - 1.0)

    def _effective_age(self, wear_per_chip: float) -> float:
        return self.now + self.config.util_weight * wear_per_chip

    def pod_rate(self, pod: int) -> float:
        """Per-chip hazard rate of one pod at its effective age; flavors
        without pod identity fall back to the fleet mean."""
        if self._num_pods and self._pod_chips:
            wpc = self.wear.get(pod, 0.0) / self._pod_chips
            return self._rate(self._effective_age(wpc))
        return self._fleet_rate()

    def _fleet_rate(self) -> float:
        """Fleet-mean per-chip hazard rate (the relative-heat baseline).
        Flavors without pod identity (GPU tree, flat pool) read the
        fleet-wide wear bucket, so ``hazard_util`` still ages a busy
        fleet faster than an idle one — uniformly, since no per-unit
        wear is tracked there."""
        if self._num_pods and self._pod_chips:
            wpc = self._wear_total / (self._num_pods * self._pod_chips)
        elif self._total_chips:
            wpc = self._wear_total / self._total_chips
        else:
            wpc = 0.0
        return self._rate(self._effective_age(wpc))

    def score(self, cluster, scope) -> float:
        """Expected failure arrivals per hour over ``scope``'s chips at
        their effective age — the age/utilization half of
        ``cluster.hazard_score`` (flavors add their degrade-mask penalty
        on top).  ``("pod", p)`` scopes use that pod's own wear; other
        scopes fall back to the fleet mean."""
        from gpuschedule_tpu.faults.schedule import scope_capacity

        chips = scope_capacity(cluster, scope)
        if chips <= 0:
            return 0.0
        if scope[0] == "pod" and self._num_pods:
            rate = self.pod_rate(int(scope[1]))
        elif scope[0] in ("chip", "box") and self._num_pods:
            rate = self.pod_rate(int(scope[1]))
        else:
            rate = self._fleet_rate()
        return chips * rate * 3600.0

    def gang_exposure(self, allocation) -> float:
        """Relative hazard heat of one allocation's hardware in [0, 1]:
        how much hotter than the fleet mean its pods run (0 when its
        pods sit at or below the mean — uniform wear scores 0 for
        everyone).  Feeds the engine's proactive-migrate exposure next
        to the gang's lost straggler rate."""
        if not self._num_pods:
            return 0.0
        detail = getattr(allocation, "detail", None)
        slices = getattr(detail, "slices", None)
        if slices:
            pods = sorted({s.pod for s in slices})
        else:
            pod = getattr(detail, "pod", None)
            if pod is None:
                return 0.0
            pods = [pod]
        base = self._fleet_rate()
        if base <= 0.0:
            return 0.0
        heat = sum(self.pod_rate(p) for p in pods) / (len(pods) * base)
        return min(1.0, max(0.0, heat - 1.0))
