"""The declared seed-stream registry (ISSUE 13, rule GS2xx).

The seed-split rule (faults/schedule.py, PR 2): one ``--seed`` governs
every stochastic stream in a run — trace synthesis keeps the bare seed,
and every other process derives an independent stream as
``random.Random(f"{seed}:<namespace>")``.  Two processes sharing a
namespace silently share a stream (draws interleave, determinism
contracts break one knob at a time), so every namespace template used
anywhere in the package must be REGISTERED here, and each template may
be constructed at exactly one call site (GS203) unless listed as
deliberately shared.

Templates are the f-string with every interpolation hole normalized to
``{}`` — ``f"{seed}:faults:mtbf"`` registers as ``{}:faults:mtbf``.
Adding a stream: pick a namespace no other process uses, add the row
here with a one-line description, then construct it.  The linter flags
unregistered templates (GS201), stale registry rows (GS202), and
duplicate construction sites (GS203).
"""

from __future__ import annotations

# template -> what draws from it
SEED_STREAMS = {
    "{}:faults:mtbf": "per-chip MTBF outages (faults/schedule.py); the "
                      "Weibull hazard sampler time-rescales this same "
                      "stream so shape=1 stays draw-identical",
    "{}:faults:spot": "spot revocations (+ pre-revoke warnings)",
    "{}:faults:link": "DCN uplink degradation outages",
    "{}:faults:domain": "correlated host/rack/pod blast-radius outages",
    "{}:faults:straggler": "slow-chip onset/recovery",
    "{}:net:share": "deterministic multislice promotion in the "
                    "contention sweep grid (net/sweep.py)",
}

# templates deliberately constructed at more than one call site (none
# today; the hazard sampler reuses the mtbf stream by replaying the SAME
# RNG object, not by re-deriving the namespace)
SHARED_SEED_STREAMS: tuple = ()
