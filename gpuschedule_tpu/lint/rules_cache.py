"""GS5xx — cache-discipline rules (ISSUE 13, precision ISSUE 14).

The PR-7/9/11 speed lattice is a web of caches whose correctness rests
on two conventions with no runtime check:

- every cache exposed through the unified ``engine_cache_events``
  telemetry family (a ``cache_stats()`` method returning
  ``{cache: {outcome: counter}}``) must have LIVE counter sites — a
  counter attribute that is never incremented reads as a permanently-
  cold cache in the Engine-health panel (**GS501**), and a declared
  cache name absent from ``docs/events.md`` is schema drift in the
  ``cache`` record's documentation (**GS503**).  ISSUE 14: liveness is
  CLASS-QUALIFIED through the symbol table — the counter expression's
  owner class is resolved (``self.x`` -> the declaring class;
  ``self._group_cache.reused`` -> the class ``_group_cache`` was
  constructed with), and only increments attributable to that owner
  (``self.x += 1`` in its methods, or ``p.x += 1`` through a parameter
  annotated with the owner class) keep it alive — a same-named counter
  in an unrelated class no longer masks a dead one.  An increment whose
  owner cannot be resolved still counts for any owner (conservative:
  unknown suppresses, never invents, a finding);
- every derived cache on a snapshot-capable class must be shed in
  ``__getstate__`` or rebuilt in ``restored()`` (the ISSUE 11 snapshot
  contract: a resume never trusts pre-snapshot geometry).  The class
  declares its derived caches in a ``_DERIVED_CACHES`` tuple; this rule
  cross-checks the declaration against both hooks in BOTH directions
  (**GS502**).  ISSUE 14: NON-cache snapshot metadata handled in those
  hooks (a schema stamp, a format version) is declared in a
  ``_SNAPSHOT_META`` tuple instead of being misread as an undeclared
  cache; a ``_SNAPSHOT_META`` entry no hook touches, or one that also
  appears in ``_DERIVED_CACHES``, is flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    backtick_tokens,
    const_str,
    rule,
)

# (defining path, class name) or None when unresolvable
OwnerKey = Optional[Tuple[str, str]]


def _counter_owner(
    node: ast.AST, path: str, cls: Optional[str], symbols
) -> Tuple[OwnerKey, Optional[str]]:
    """Resolve a counter expression to (owner class, attribute):
    ``self.x`` -> the enclosing class; ``self.a.b`` -> the class
    ``self.a`` was constructed with (symbol-table provenance);
    ``name.b`` -> the annotated class of parameter/local ``name`` when
    known.  Unresolvable owners return (None, attr)."""
    if not isinstance(node, ast.Attribute):
        if isinstance(node, ast.Name):
            return None, node.id
        return None, None
    attr = node.attr
    base = node.value
    if isinstance(base, ast.Name):
        if base.id == "self" and cls is not None:
            return (path, cls), attr
        return None, attr
    if (
        isinstance(base, ast.Attribute)
        and isinstance(base.value, ast.Name)
        and base.value.id == "self"
        and cls is not None
    ):
        owner = symbols.class_attr_types.get((path, cls), {}).get(base.attr)
        return owner, attr
    return None, attr


def _counter_tokens_in_dict(
    d: ast.Dict, path: str, cls: Optional[str], symbols
) -> List[Tuple[str, OwnerKey, str]]:
    """(outcome, owner, counter attribute) triples from an
    ``{"hit": self.x, ...}`` literal; non-attribute counters yield no
    token (computed expressions can't be increment-checked)."""
    out = []
    for k, v in zip(d.keys, d.values):
        outcome = const_str(k) if k is not None else None
        owner, token = _counter_owner(v, path, cls, symbols)
        if outcome and token:
            out.append((outcome, owner, token))
    return out


def _declared_caches(
    ctx: LintContext, symbols
) -> Dict[str, Tuple[str, int, List[Tuple[str, OwnerKey, str]]]]:
    """cache name -> (path, line, [(outcome, owner, counter attr)]) from
    every ``cache_stats`` method in the package: dict-literal returns
    plus ``stats["name"] = {...}`` subscript stores."""
    caches: Dict[str, Tuple[str, int, List[Tuple[str, OwnerKey, str]]]] = {}
    for (path, cls, fname), node in sorted(
        symbols.functions.items(),
        key=lambda kv: (kv[0][0], kv[1].lineno),
    ):
        if fname != "cache_stats" or cls is None:
            continue
        for sub in ast.walk(node):
            pairs: Dict[str, ast.Dict] = {}
            if isinstance(sub, ast.Return) and isinstance(
                sub.value, ast.Dict
            ):
                for k, v in zip(sub.value.keys, sub.value.values):
                    name = const_str(k) if k is not None else None
                    if name and isinstance(v, ast.Dict):
                        pairs[name] = v
            elif isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(sub.value, ast.Dict)
                    ):
                        name = const_str(t.slice)
                        if name:
                            pairs[name] = sub.value
                # out = {...} literal bodies inside cache_stats
                if (
                    len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Dict)
                ):
                    for k, v in zip(sub.value.keys, sub.value.values):
                        name = const_str(k) if k is not None else None
                        if name and isinstance(v, ast.Dict):
                            pairs[name] = v
            for name, d in pairs.items():
                caches.setdefault(
                    name,
                    (path, d.lineno,
                     _counter_tokens_in_dict(d, path, cls, symbols)),
                )
    return caches


def _incremented_attrs(
    ctx: LintContext, symbols
) -> Tuple[Set[Tuple[Tuple[str, str], str]], Set[str]]:
    """(owner-resolved increments, owner-unknown increment attrs):
    every augmented-assignment target in the package (pre-collected by
    the symbol table), attributed to its owner class where resolvable."""
    owned: Set[Tuple[Tuple[str, str], str]] = set()
    bare: Set[str] = set()
    for path, cls, fkey, target in symbols.aug_assigns:
        if isinstance(target, ast.Name):
            bare.add(target.id)
            continue
        if not isinstance(target, ast.Attribute):
            continue
        attr = target.attr
        base = target.value
        owner: OwnerKey = None
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                owner = (path, cls)
            elif fkey is not None:
                owner = symbols.param_class(fkey, base.id)
        elif (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
            and cls is not None
        ):
            owner = symbols.class_attr_types.get(
                (path, cls), {}
            ).get(base.attr)
        if owner is not None:
            owned.add((owner, attr))
        else:
            bare.add(attr)
    return owned, bare


@rule(codes=("GS501", "GS503"))
def cache_telemetry_liveness(ctx: LintContext) -> List[Finding]:
    symbols = ctx.symbols()
    caches = _declared_caches(ctx, symbols)
    if not caches:
        return []
    owned, bare = _incremented_attrs(ctx, symbols)
    out: List[Finding] = []
    for name in sorted(caches):
        path, line, counters = caches[name]
        for outcome, owner, token in counters:
            live = token in bare or (
                owner is not None and (owner, token) in owned
            )
            if owner is None:
                # unresolvable owner: fall back to any-owner increments
                live = live or any(a == token for _, a in owned)
            if not live:
                out.append(Finding(
                    "GS501", path, line, 0,
                    f"cache '{name}' outcome '{outcome}' reads counter "
                    f"'{token}' that is never incremented on its owner "
                    "class — dead telemetry",
                    f"{name}.{outcome}",
                ))
    # GS503: every declared cache name must appear in docs/events.md
    doc_path = ctx.config.events_doc_path
    if ctx.has(doc_path):
        tokens = backtick_tokens(ctx.source(doc_path))
        for name in sorted(caches):
            path, line, _ = caches[name]
            if name not in tokens:
                out.append(Finding(
                    "GS503", path, line, 0,
                    f"cache '{name}' rides the engine_cache_events "
                    f"family but appears nowhere in {doc_path} — "
                    "document it in the `cache` record row",
                    name,
                ))
    return out


def _class_tuple_decl(
    cls: ast.ClassDef, decl_name: str
) -> Optional[Tuple[Set[str], int]]:
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == decl_name:
                    names: Set[str] = set()
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        for el in node.value.elts:
                            s = const_str(el)
                            if s:
                                names.add(s)
                    return names, node.lineno
    return None


def _shed_keys(cls: ast.ClassDef) -> Set[str]:
    """Keys assigned into the state dict inside ``__getstate__``."""
    keys: Set[str] = set()
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__getstate__":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Subscript):
                            s = const_str(t.slice)
                            if s:
                                keys.add(s)
    return keys


def _rebuilt_attrs(cls: ast.ClassDef) -> Set[str]:
    """``self.X = ...`` targets inside ``restored()``."""
    attrs: Set[str] = set()
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "restored":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            attrs.add(t.attr)
    return attrs


@rule(codes=("GS502",))
def derived_cache_snapshot_coverage(ctx: LintContext) -> List[Finding]:
    symbols = ctx.symbols()
    out: List[Finding] = []
    for (path, _clsname), node in sorted(
        symbols.classes.items(), key=lambda kv: (kv[0][0], kv[1].lineno)
    ):
        decl = _class_tuple_decl(node, "_DERIVED_CACHES")
        meta = _class_tuple_decl(node, "_SNAPSHOT_META")
        shed = _shed_keys(node)
        rebuilt = _rebuilt_attrs(node)
        touched = shed | rebuilt
        meta_names = meta[0] if meta is not None else set()
        if decl is None and meta is None:
            if touched:
                out.append(Finding(
                    "GS502", path, node.lineno, node.col_offset,
                    f"class {node.name} sheds/rebuilds state in "
                    "__getstate__/restored() but declares neither "
                    "_DERIVED_CACHES nor _SNAPSHOT_META — the "
                    "snapshot contract is unauditable without a "
                    "declaration",
                    f"{node.name}:undeclared",
                ))
            continue
        declared, line = decl if decl is not None else (set(), 0)
        if meta is not None and line == 0:
            line = meta[1]
        for name in sorted(declared & meta_names):
            out.append(Finding(
                "GS502", path, line, 0,
                f"{node.name} declares '{name}' in BOTH "
                "_DERIVED_CACHES and _SNAPSHOT_META — it is either "
                "a rebuildable cache or snapshot metadata, not both",
                f"{node.name}:{name}:dual-declared",
            ))
        for name in sorted(declared - touched):
            out.append(Finding(
                "GS502", path, line, 0,
                f"{node.name}._DERIVED_CACHES declares '{name}' but "
                "__getstate__ does not shed it and restored() does "
                "not rebuild it — a resume would trust pre-snapshot "
                "state",
                f"{node.name}:{name}:unshed",
            ))
        for name in sorted(meta_names - touched):
            out.append(Finding(
                "GS502", path, line, 0,
                f"{node.name}._SNAPSHOT_META declares '{name}' but "
                "neither __getstate__ nor restored() touches it — "
                "stale metadata declaration",
                f"{node.name}:{name}:meta-stale",
            ))
        for name in sorted(touched - declared - meta_names):
            out.append(Finding(
                "GS502", path, line, 0,
                f"{node.name} sheds/rebuilds '{name}' without "
                "declaring it in _DERIVED_CACHES (a rebuildable "
                "cache) or _SNAPSHOT_META (non-cache snapshot "
                "metadata) — declare it so the snapshot contract "
                "stays auditable",
                f"{node.name}:{name}:undeclared",
            ))
    return out
