"""GS5xx — cache-discipline rules (ISSUE 13).

The PR-7/9/11 speed lattice is a web of caches whose correctness rests
on two conventions with no runtime check:

- every cache exposed through the unified ``engine_cache_events``
  telemetry family (a ``cache_stats()`` method returning
  ``{cache: {outcome: counter}}``) must have LIVE counter sites — a
  counter attribute that is never incremented anywhere reads as a
  permanently-cold cache in the Engine-health panel (**GS501**), and a
  declared cache name absent from ``docs/events.md`` is schema drift in
  the ``cache`` record's documentation (**GS503**);
- every derived cache on a snapshot-capable class must be shed in
  ``__getstate__`` or rebuilt in ``restored()`` (the ISSUE 11 snapshot
  contract: a resume never trusts pre-snapshot geometry).  The class
  declares its derived caches in a ``_DERIVED_CACHES`` tuple; this rule
  cross-checks the declaration against both hooks in BOTH directions
  (**GS502**) — an undeclared shed is as much drift as an unshed
  declaration, and a class that sheds state without any declaration is
  flagged too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    backtick_tokens,
    const_str,
    rule,
)


def _last_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _counter_tokens_in_dict(d: ast.Dict) -> List[Tuple[str, str]]:
    """(outcome, counter-attribute token) pairs from an
    ``{"hit": self.x, ...}`` literal; non-constant counters yield no
    token (computed expressions can't be increment-checked)."""
    out = []
    for k, v in zip(d.keys, d.values):
        outcome = const_str(k) if k is not None else None
        token = _last_attr(v)
        if outcome and token:
            out.append((outcome, token))
    return out


def _declared_caches(
    ctx: LintContext,
) -> Dict[str, Tuple[str, int, List[Tuple[str, str]]]]:
    """cache name -> (path, line, [(outcome, counter token)]) from every
    ``cache_stats`` method in the package: dict-literal returns plus
    ``stats["name"] = {...}`` subscript stores."""
    caches: Dict[str, Tuple[str, int, List[Tuple[str, str]]]] = {}
    for path in ctx.py_files:
        for node in ast.walk(ctx.tree(path)):
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name != "cache_stats":
                continue
            for sub in ast.walk(node):
                pairs: Dict[str, ast.Dict] = {}
                if isinstance(sub, ast.Return) and isinstance(
                    sub.value, ast.Dict
                ):
                    for k, v in zip(sub.value.keys, sub.value.values):
                        name = const_str(k) if k is not None else None
                        if name and isinstance(v, ast.Dict):
                            pairs[name] = v
                elif isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Subscript)
                            and isinstance(sub.value, ast.Dict)
                        ):
                            name = const_str(t.slice)
                            if name:
                                pairs[name] = sub.value
                    # out = {...} literal bodies inside cache_stats
                    if (
                        len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)
                        and isinstance(sub.value, ast.Dict)
                    ):
                        for k, v in zip(sub.value.keys, sub.value.values):
                            name = const_str(k) if k is not None else None
                            if name and isinstance(v, ast.Dict):
                                pairs[name] = v
                for name, d in pairs.items():
                    caches.setdefault(
                        name,
                        (path, d.lineno, _counter_tokens_in_dict(d)),
                    )
    return caches


def _incremented_attrs(ctx: LintContext) -> Set[str]:
    """Every attribute/name that is the target of an augmented
    assignment anywhere in the package."""
    incs: Set[str] = set()
    for path in ctx.py_files:
        for node in ast.walk(ctx.tree(path)):
            if isinstance(node, ast.AugAssign):
                token = _last_attr(node.target)
                if token:
                    incs.add(token)
    return incs


@rule
def cache_telemetry_liveness(ctx: LintContext) -> List[Finding]:
    caches = _declared_caches(ctx)
    if not caches:
        return []
    incremented = _incremented_attrs(ctx)
    out: List[Finding] = []
    for name in sorted(caches):
        path, line, counters = caches[name]
        for outcome, token in counters:
            if token not in incremented:
                out.append(Finding(
                    "GS501", path, line, 0,
                    f"cache '{name}' outcome '{outcome}' reads counter "
                    f"'{token}' that is never incremented anywhere — "
                    "dead telemetry",
                    f"{name}.{outcome}",
                ))
    # GS503: every declared cache name must appear in docs/events.md
    doc_path = ctx.config.events_doc_path
    if ctx.has(doc_path):
        tokens = backtick_tokens(ctx.source(doc_path))
        for name in sorted(caches):
            path, line, _ = caches[name]
            if name not in tokens:
                out.append(Finding(
                    "GS503", path, line, 0,
                    f"cache '{name}' rides the engine_cache_events "
                    f"family but appears nowhere in {doc_path} — "
                    "document it in the `cache` record row",
                    name,
                ))
    return out


def _class_derived_decl(cls: ast.ClassDef) -> Optional[Tuple[Set[str], int]]:
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "_DERIVED_CACHES":
                    names: Set[str] = set()
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        for el in node.value.elts:
                            s = const_str(el)
                            if s:
                                names.add(s)
                    return names, node.lineno
    return None


def _shed_keys(cls: ast.ClassDef) -> Set[str]:
    """Keys assigned into the state dict inside ``__getstate__``."""
    keys: Set[str] = set()
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__getstate__":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Subscript):
                            s = const_str(t.slice)
                            if s:
                                keys.add(s)
    return keys


def _rebuilt_attrs(cls: ast.ClassDef) -> Set[str]:
    """``self.X = ...`` targets inside ``restored()``."""
    attrs: Set[str] = set()
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == "restored":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            attrs.add(t.attr)
    return attrs


@rule
def derived_cache_snapshot_coverage(ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    for path in ctx.py_files:
        for node in ast.walk(ctx.tree(path)):
            if not isinstance(node, ast.ClassDef):
                continue
            decl = _class_derived_decl(node)
            shed = _shed_keys(node)
            rebuilt = _rebuilt_attrs(node)
            if decl is None:
                if shed or rebuilt:
                    out.append(Finding(
                        "GS502", path, node.lineno, node.col_offset,
                        f"class {node.name} sheds/rebuilds state in "
                        "__getstate__/restored() but declares no "
                        "_DERIVED_CACHES tuple — the snapshot contract "
                        "is unauditable without the declaration",
                        f"{node.name}:undeclared",
                    ))
                continue
            declared, line = decl
            for name in sorted(declared - (shed | rebuilt)):
                out.append(Finding(
                    "GS502", path, line, 0,
                    f"{node.name}._DERIVED_CACHES declares '{name}' but "
                    "__getstate__ does not shed it and restored() does "
                    "not rebuild it — a resume would trust pre-snapshot "
                    "state",
                    f"{node.name}:{name}:unshed",
                ))
            for name in sorted((shed | rebuilt) - declared):
                out.append(Finding(
                    "GS502", path, line, 0,
                    f"{node.name} sheds/rebuilds '{name}' without "
                    "declaring it in _DERIVED_CACHES — declare it so the "
                    "snapshot contract stays auditable",
                    f"{node.name}:{name}:undeclared",
                ))
    return out
