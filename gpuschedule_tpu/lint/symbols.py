"""Package-wide symbol table + call graph (ISSUE 14 tentpole).

PR 13's rules were per-file pattern matchers: set-iteration detection
saw only local bindings and ``self`` attributes of the enclosing class,
counter liveness matched attribute names package-wide with no notion of
*which* class owns the counter, and nothing could follow a value through
a ``from``-import or a function return.  This module is the shared
whole-program layer those rules (and the new GS7xx state-machine family)
now sit on:

- **import resolution**: every ``from``-import resolved to its source
  module (absolute dotted, or relative against the importing file's
  package) — one implementation, shared with the fork-safety rule;
- **set provenance**: which module-level names are bound to sets, which
  functions/methods *return* sets, and which ``self`` attributes hold
  sets — propagated across imports, function returns, and attribute
  assignment to a fixed point, so a set built in ``cluster/base.py``
  and iterated in ``sim/engine.py`` is detectable;
- **class provenance**: the class an attribute holds (``self._cache =
  GroupCache()`` types ``_cache`` as ``GroupCache``, following the
  import to its defining module), plus annotation-based typing of
  function parameters (``cache: Optional[GroupCache]``) — what lets
  counter liveness be class-qualified;
- **call graph**: best-effort resolved edges (bare names, ``self``
  methods, imported functions, module-qualified calls) for rules that
  need caller context.

Documented limits (docs/static-analysis.md): inference is assignment-
and annotation-driven — no inheritance walking, no container-element
typing, no flow-sensitivity.  A name the table cannot classify is
*unknown*, and every consuming rule treats unknown conservatively
(suppressing, never inventing, a finding) except where it demands an
explicit annotation (GS703).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

# (path, enclosing class name or None, function name)
FuncKey = Tuple[str, Optional[str], str]
# (defining path, class name)
ClassKey = Tuple[str, str]

_SET_CONSTRUCTORS = {"set", "frozenset"}
_SET_ANNOTATIONS = {"set", "frozenset", "Set", "FrozenSet", "MutableSet",
                    "AbstractSet"}
# wrappers whose result is NOT a set even when fed one
_ORDERING_CALLS = {"sorted", "list", "tuple"}


def module_dotted(path: str) -> str:
    """gpuschedule_tpu/sim/whatif.py -> gpuschedule_tpu.sim.whatif"""
    return path[:-3].replace("/__init__", "").replace("/", ".")


def containing_package(path: str) -> str:
    """The dotted package a file's relative imports resolve against."""
    if path.endswith("/__init__.py"):
        return module_dotted(path)
    return module_dotted(path).rsplit(".", 1)[0]


def _annotation_names(node: Optional[ast.AST]) -> List[str]:
    """Flatten an annotation expression to its identifier leaves:
    ``Optional[GroupCache]`` -> ["Optional", "GroupCache"]."""
    out: List[str] = []
    if node is None:
        return out
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            # string annotations: parse the forward reference
            try:
                out.extend(_annotation_names(ast.parse(sub.value, mode="eval").body))
            except SyntaxError:
                pass
    return out


def _is_set_annotation(node: Optional[ast.AST]) -> bool:
    names = _annotation_names(node)
    return bool(names) and names[0] in _SET_ANNOTATIONS


def bound_names(fn: ast.AST) -> Set[str]:
    """Every name BOUND inside a function scope other than by a plain
    assignment: parameters (own and nested defs'), loop / with /
    except / comprehension targets, nested def names.  Consumers seed
    these as NON-sets so a binding that shadows a module-level set is
    never misread as that set (plain assignments stay flow-classified
    by the caller and may override)."""
    out: Set[str] = set()

    def targets(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                targets(el)
        elif isinstance(t, ast.Starred):
            targets(t.value)

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            a = node.args
            for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs,
                        a.vararg, a.kwarg):
                if arg is not None:
                    out.add(arg.arg)
            if not isinstance(node, ast.Lambda):
                out.add(node.name)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            targets(node.target)
        elif isinstance(node, ast.comprehension):
            targets(node.target)
        elif isinstance(node, ast.withitem):
            if node.optional_vars is not None:
                targets(node.optional_vars)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            out.add(node.name)
    return out


class SymbolTable:
    """Parsed-once whole-program view.  Build via
    ``LintContext.symbols()`` — construction walks every package AST a
    small constant number of times (the set/return classification runs
    to a fixed point, bounded by the import-chain depth)."""

    def __init__(self, ctx) -> None:
        self._ctx = ctx
        self.paths: List[str] = list(ctx.py_files)
        self._path_of_module: Dict[str, str] = {}
        for p in self.paths:
            self._path_of_module[module_dotted(p)] = p

        # per-module import maps
        # local name -> (source module dotted, remote symbol name)
        self.from_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        # local alias -> module dotted ("import x.y as z")
        self.module_aliases: Dict[str, Dict[str, str]] = {}

        # definitions
        self.functions: Dict[FuncKey, ast.AST] = {}
        self.classes: Dict[ClassKey, ast.ClassDef] = {}

        # provenance
        self.module_sets: Dict[str, Set[str]] = {}
        self.set_returning: Set[FuncKey] = set()
        self.class_set_attrs: Dict[ClassKey, Set[str]] = {}
        self.class_attr_types: Dict[ClassKey, Dict[str, ClassKey]] = {}

        # call graph: caller -> set of resolved callees
        self.calls: Dict[FuncKey, Set[FuncKey]] = {}

        # pre-collected AST slices the fixpoint re-reads (walking the
        # trees once here instead of once per iteration keeps the whole
        # build inside the CI gate's wall-time budget)
        self._module_binds: Dict[str, List[tuple]] = {}
        self._fn_rets: Dict[FuncKey, List[ast.AST]] = {}
        self._fn_assigns: Dict[FuncKey, List[Tuple[str, ast.AST]]] = {}
        self._cls_attrs: Dict[ClassKey, List[tuple]] = {}
        # every augmented-assignment target with its context, for the
        # class-qualified counter-liveness rule:
        # (path, enclosing class, enclosing FuncKey or None, target)
        self.aug_assigns: List[
            Tuple[str, Optional[str], Optional[FuncKey], ast.AST]
        ] = []
        self._fn_bound: Dict[FuncKey, Set[str]] = {}

        for path in self.paths:
            self._index_module(path)
        self._classify_fixpoint()
        self._build_call_graph()

    # ---------------------------------------------------------------- #
    # indexing

    def _index_module(self, path: str) -> None:
        tree = self._ctx.tree(path)
        package = containing_package(path)
        froms: Dict[str, Tuple[str, str]] = {}
        mods: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mods[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    resolved = node.module or ""
                else:
                    parts = package.split(".")
                    parts = parts[: len(parts) - (node.level - 1)]
                    if node.module:
                        parts.append(node.module)
                    resolved = ".".join(parts)
                for a in node.names:
                    froms[a.asname or a.name] = (resolved, a.name)
        self.from_imports[path] = froms
        self.module_aliases[path] = mods

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[(path, None, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[(path, node.name)] = node
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.functions[(path, node.name, sub.name)] = sub

        # module-level bindings: (target names, value, set-annotated?)
        binds: List[tuple] = []
        for node in tree.body:
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if names:
                    binds.append((names, node.value, False))
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                binds.append((
                    [node.target.id], node.value,
                    _is_set_annotation(node.annotation),
                ))
        self._module_binds[path] = binds

        # per-function return values, straight-line Name assigns, and
        # augmented-assignment sites (one walk serves all three)
        for key, fn in list(self.functions.items()):
            if key[0] != path or key in self._fn_rets:
                continue
            rets: List[ast.AST] = []
            assigns: List[Tuple[str, ast.AST]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    if not (isinstance(node.value, ast.Constant)
                            and node.value.value is None):
                        rets.append(node.value)
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            assigns.append((t.id, node.value))
                elif isinstance(node, ast.AugAssign):
                    self.aug_assigns.append((path, key[1], key, node.target))
            self._fn_rets[key] = rets
            self._fn_assigns[key] = assigns
        # module- and class-body-level augmented assignments (no
        # enclosing function)
        for node in tree.body:
            if isinstance(node, ast.AugAssign):
                self.aug_assigns.append((path, None, None, node.target))
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.AugAssign):
                        self.aug_assigns.append(
                            (path, node.name, None, sub.target)
                        )

        # per-class self-attribute sites: (attr, value, annotation)
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            sites: List[tuple] = []
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            sites.append((t.attr, sub.value, None))
                elif isinstance(sub, ast.AnnAssign):
                    t = sub.target
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        sites.append((t.attr, sub.value, sub.annotation))
                    elif isinstance(t, ast.Name) and sub.value is None:
                        sites.append((t.id, None, sub.annotation))
            self._cls_attrs[(path, node.name)] = sites

    # ---------------------------------------------------------------- #
    # resolution helpers

    def path_of_module(self, dotted: str) -> Optional[str]:
        return self._path_of_module.get(dotted)

    def resolve_import(self, path: str, name: str) -> Optional[Tuple[str, str]]:
        """Local ``name`` in ``path`` -> (source path, symbol name) when
        it is a from-import of another package module."""
        hit = self.from_imports.get(path, {}).get(name)
        if hit is None:
            return None
        src = self.path_of_module(hit[0])
        if src is None:
            return None
        return src, hit[1]

    def resolve_class(self, path: str, name: str) -> Optional[ClassKey]:
        """A class name referenced in ``path`` -> its defining
        (path, class), following one from-import hop."""
        if (path, name) in self.classes:
            return (path, name)
        imp = self.resolve_import(path, name)
        if imp is not None and (imp[0], imp[1]) in self.classes:
            return (imp[0], imp[1])
        return None

    def resolve_callable(
        self, path: str, cls: Optional[str], func: ast.AST
    ) -> Optional[FuncKey]:
        """Resolve a Call's func expression to a known FuncKey:
        bare names (module functions + from-imports), ``self.m``
        methods, and ``mod.f`` module-qualified calls."""
        if isinstance(func, ast.Name):
            if (path, None, func.id) in self.functions:
                return (path, None, func.id)
            imp = self.resolve_import(path, func.id)
            if imp is not None and (imp[0], None, imp[1]) in self.functions:
                return (imp[0], None, imp[1])
            return None
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            base = func.value.id
            if base == "self" and cls is not None:
                if (path, cls, func.attr) in self.functions:
                    return (path, cls, func.attr)
                return None
            mod = self.module_aliases.get(path, {}).get(base)
            if mod is not None:
                target = self.path_of_module(mod)
                if target is not None and (target, None, func.attr) in self.functions:
                    return (target, None, func.attr)
        return None

    # ---------------------------------------------------------------- #
    # set provenance

    def expr_is_set(
        self,
        path: str,
        cls: Optional[str],
        node: ast.AST,
        local_sets: Optional[Set[str]] = None,
        local_nonsets: Optional[Set[str]] = None,
    ) -> bool:
        """Whether an expression provably evaluates to a set.
        ``local_sets`` / ``local_nonsets`` are the caller's per-function
        binding classification; names in neither fall back to module /
        import provenance."""
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.IfExp):
            # conservative: both arms must be sets
            return self.expr_is_set(path, cls, node.body, local_sets,
                                    local_nonsets) and self.expr_is_set(
                path, cls, node.orelse, local_sets, local_nonsets)
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in _SET_CONSTRUCTORS:
                return True
            if isinstance(f, ast.Name) and f.id in _ORDERING_CALLS:
                return False
            key = self.resolve_callable(path, cls, f)
            return key is not None and key in self.set_returning
        if isinstance(node, ast.Name):
            if local_sets is not None and node.id in local_sets:
                return True
            if local_nonsets is not None and node.id in local_nonsets:
                return False
            if node.id in self.module_sets.get(path, ()):
                return True
            imp = self.resolve_import(path, node.id)
            if imp is not None:
                return imp[1] in self.module_sets.get(imp[0], ())
            return False
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
        ):
            if node.value.id == "self" and cls is not None:
                return node.attr in self.class_set_attrs.get((path, cls), ())
            mod = self.module_aliases.get(path, {}).get(node.value.id)
            if mod is not None:
                target = self.path_of_module(mod)
                if target is not None:
                    return node.attr in self.module_sets.get(target, ())
        return False

    def _function_returns_set(self, key: FuncKey) -> bool:
        path, cls, _name = key
        returns = self._fn_rets.get(key, ())
        if not returns:
            return False
        # simple local classification: straight-line Name = <expr>;
        # params / loop targets pre-seed as NON-sets so a name that
        # shadows a module-level set is never misread as it (memoized —
        # the fixpoint revisits unclassified functions every iteration)
        bound = self._fn_bound.get(key)
        if bound is None:
            bound = self._fn_bound[key] = bound_names(self.functions[key])
        local_sets: Set[str] = set()
        local_nonsets: Set[str] = set(bound)
        for name, value in self._fn_assigns.get(key, ()):
            if self.expr_is_set(path, cls, value, local_sets, local_nonsets):
                local_sets.add(name)
                local_nonsets.discard(name)
            else:
                local_nonsets.add(name)
                local_sets.discard(name)
        return all(
            self.expr_is_set(path, cls, r, local_sets, local_nonsets)
            for r in returns
        )

    def _classify_fixpoint(self) -> None:
        """Iterate module-set / set-returning / class-attr classification
        until stable — bounded by the longest provenance chain, tiny in
        practice.  Reads the pre-collected AST slices, so each iteration
        costs O(bindings), not a full tree walk."""
        for _ in range(6):
            changed = False
            # module-level set names
            for path in self.paths:
                names = self.module_sets.setdefault(path, set())
                for targets, value, annotated in self._module_binds[path]:
                    is_set = annotated or (
                        value is not None
                        and self.expr_is_set(path, None, value)
                    )
                    if is_set:
                        for t in targets:
                            if t not in names:
                                names.add(t)
                                changed = True
            # set-returning functions
            for key in self.functions:
                if key not in self.set_returning and self._function_returns_set(key):
                    self.set_returning.add(key)
                    changed = True
            # class set attributes (assignment-, annotation-, and
            # call-provenance driven)
            for (path, clsname) in self.classes:
                attrs = self.class_set_attrs.setdefault((path, clsname), set())
                for target, value, annotation in self._cls_attrs[
                    (path, clsname)
                ]:
                    is_set = _is_set_annotation(annotation) or (
                        value is not None
                        and self.expr_is_set(path, clsname, value)
                    )
                    if is_set and target not in attrs:
                        attrs.add(target)
                        changed = True
            if not changed:
                break

        # class attribute types (single pass; no fixpoint needed — the
        # right-hand side is a direct constructor call)
        for (path, clsname) in self.classes:
            types = self.class_attr_types.setdefault((path, clsname), {})
            for target, value, _annotation in self._cls_attrs[(path, clsname)]:
                if isinstance(value, ast.IfExp):
                    # `GroupCache() if armed else None` — type from the
                    # constructing arm
                    for arm in (value.body, value.orelse):
                        if isinstance(arm, ast.Call):
                            value = arm
                            break
                if not (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Name)):
                    continue
                resolved = self.resolve_class(path, value.func.id)
                if resolved is not None:
                    types.setdefault(target, resolved)

    # ---------------------------------------------------------------- #
    # call graph

    def _build_call_graph(self) -> None:
        for key, fn in self.functions.items():
            path, cls, _ = key
            edges = self.calls.setdefault(key, set())
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = self.resolve_callable(path, cls, node.func)
                    if callee is not None:
                        edges.add(callee)

    def callers_of(self, key: FuncKey) -> List[FuncKey]:
        return sorted(
            caller for caller, callees in self.calls.items()
            if key in callees
        )

    # ---------------------------------------------------------------- #
    # parameter typing (annotation-driven)

    def param_class(
        self, key: FuncKey, param: str
    ) -> Optional[ClassKey]:
        """The class a function parameter is annotated with (following
        one import hop); None when unannotated or unresolvable."""
        fn = self.functions.get(key)
        if fn is None:
            return None
        a = fn.args
        for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            if arg.arg == param and arg.annotation is not None:
                for name in _annotation_names(arg.annotation):
                    resolved = self.resolve_class(key[0], name)
                    if resolved is not None:
                        return resolved
        return None
