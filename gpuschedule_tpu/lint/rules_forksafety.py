"""GS6xx — fork-safety rule (ISSUE 13).

The what-if pool (PR 12) and the sweep grids (PR 7) run the engine in
forked/spawned worker processes.  Module-level mutable state that is
MUTATED at runtime is the classic fork hazard: under ``fork()`` every
worker silently shares the parent's pre-fork contents, and under
``spawn`` it silently *doesn't* — either way the state diverges from
what a single-process run sees, and nothing says so.

**GS601** flags a module-level mutable binding (list/dict/set literal
or constructor) that some function in the package mutates — subscript
stores, ``del``, augmented assignment, or a mutating method call
(``append``/``update``/``setdefault``...).  Read-only module tables
(``GENERATIONS``, ``POLICY_CONFIGS``, ``_SPEC_KEYS``) are fine and not
flagged: they are never written after import, so every process sees the
same bytes.  Deliberate process-local state (a worker's warm-baseline
cache, an import-time registry) carries a reasoned pragma — the point
is that the sharing decision is *written down*, not inferred.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    dotted_name,
    import_aliases,
    rule,
)

_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "clear", "remove", "discard", "sort", "reverse",
    "__setitem__",
}

_MUTABLE_CONSTRUCTORS = {"list", "dict", "set", "defaultdict",
                         "OrderedDict", "Counter", "deque"}


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in _MUTABLE_CONSTRUCTORS
    return False


def _module_mutables(tree: ast.Module) -> Dict[str, Tuple[int, int, bool]]:
    """Top-level Name -> (line, col, is_sentinel) for mutable bindings
    plus ``None``-sentinel bindings (the worker-warm-state pattern:
    ``_STATE = None`` rebound under ``global`` later).  Skips __all__
    (a convention list nothing mutates by design)."""
    out: Dict[str, Tuple[int, int, bool]] = {}
    for node in tree.body:
        targets: List[ast.Name] = []
        value = None
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                targets = [node.target]
            value = node.value
        if value is None:
            continue
        sentinel = isinstance(value, ast.Constant) and value.value is None
        if not _is_mutable_literal(value) and not sentinel:
            continue
        for t in targets:
            if t.id != "__all__":
                out[t.id] = (node.lineno, node.col_offset, sentinel)
    return out


def _locals_and_globals(fn) -> Tuple[Set[str], Set[str]]:
    """One walk: names bound locally in ``fn`` (params, plain assigns,
    loop/with targets, comprehension targets — mutations of these are
    not module state; nested functions' locals fold in, an
    over-approximation that only ever suppresses, never invents, a
    finding) plus its ``global`` declarations."""
    local: Set[str] = set()
    declared_global: Set[str] = set()
    a = fn.args
    for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs, a.vararg, a.kwarg):
        if arg is not None:
            local.add(arg.arg)

    def bind(t) -> None:
        if isinstance(t, ast.Name):
            local.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                bind(el)
        elif isinstance(t, ast.Starred):
            bind(t.value)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bind(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            bind(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bind(node.target)
        elif isinstance(node, ast.withitem):
            if node.optional_vars is not None:
                bind(node.optional_vars)
        elif isinstance(node, ast.comprehension):
            bind(node.target)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            local.add(node.name)
        elif isinstance(node, ast.Global):
            declared_global.update(node.names)
    return local, declared_global


def _qualified_target(
    node: ast.AST, aliases: Dict[str, str]
) -> Optional[Tuple[str, str]]:
    """(imported module, attribute) when ``node`` is a mutation of a
    module-qualified name — ``mod.TABLE[k]`` / ``mod.TABLE`` with
    ``mod`` resolving through the file's imports."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        full = dotted_name(node, aliases)
        if full and "." in full:
            return tuple(full.rsplit(".", 1))  # type: ignore[return-value]
    return None


def _runtime_mutations(
    tree: ast.AST,
) -> Tuple[Set[str], Set[str], Set[Tuple[str, str]]]:
    """Mutation sites inside function bodies (import-time top-level
    mutation is fork-safe: it happens in every process), split three
    ways because cross-module attribution needs the distinction:

    - ``rebinds``: ``global NAME; NAME = ...`` — rebinds THIS module's
      binding only (a sibling's from-imported copy is untouched);
    - ``container``: subscript/method/del mutations of a module-level
      name — these mutate the shared OBJECT, so a from-imported name
      mutated this way blames the defining module;
    - ``qualified``: (module, attr) pairs for ``mod.NAME[...]``-style
      mutations through an imported module reference.

    Scope-aware: a function-local ``out = {}; out[k] = v`` never blames
    a same-named module global."""
    aliases = import_aliases(tree)
    rebinds: Set[str] = set()
    container: Set[str] = set()
    qualified: Set[Tuple[str, str]] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        local, declared_global = _locals_and_globals(fn)
        local -= declared_global

        def module_name(base) -> Optional[str]:
            if isinstance(base, ast.Name) and (
                base.id in declared_global or base.id not in local
            ):
                return base.id
            return None

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    # bare-Name rebind only mutates module state under
                    # an explicit ``global`` declaration
                    if isinstance(t, ast.Name) and t.id in declared_global:
                        rebinds.add(t.id)
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    base = t
                    seen_container = False
                    while isinstance(base, (ast.Subscript, ast.Attribute)):
                        if isinstance(base, ast.Subscript):
                            seen_container = True
                        base = base.value
                    if seen_container:
                        name = module_name(base)
                        if name:
                            container.add(name)
                        q = _qualified_target(t, aliases)
                        if q:
                            qualified.add(q)
                    elif isinstance(t, ast.Attribute):
                        # `mod.NAME = x`: rebinding another module's
                        # global is a mutation of that module's state
                        q = _qualified_target(t, aliases)
                        if q:
                            qualified.add(q)
                    elif (
                        isinstance(node, ast.AugAssign)
                        and isinstance(base, ast.Name)
                        and base.id in declared_global
                    ):
                        rebinds.add(base.id)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        name = module_name(t.value)
                        if name:
                            container.add(name)
                        q = _qualified_target(t, aliases)
                        if q:
                            qualified.add(q)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                    name = module_name(f.value)
                    if name:
                        container.add(name)
                    q = _qualified_target(f.value, aliases)
                    if q:
                        qualified.add(q)
    return rebinds, container, qualified


@rule(codes=("GS601",))
def module_level_mutable_state(ctx: LintContext) -> List[Finding]:
    from gpuschedule_tpu.lint.symbols import module_dotted

    symbols = ctx.symbols()
    out: List[Finding] = []
    # pass 1: each module's own candidates and mutation sites; the
    # symbol table resolves from-imports to their source module
    # (absolute dotted, or relative against the importing file's
    # package), so an unrelated module that happens to define a
    # same-named table is never blamed for a sibling's mutation
    candidates: Dict[str, Dict[str, Tuple[int, int, bool]]] = {}
    rebinds: Dict[str, Set[str]] = {}
    container: Dict[str, Set[str]] = {}
    qualified: Dict[str, Set[Tuple[str, str]]] = {}  # (module, attr)
    imports: Dict[str, Set[Tuple[str, str]]] = {}  # (resolved mod, name)
    for path in ctx.py_files:
        tree = ctx.tree(path)
        candidates[path] = _module_mutables(tree)
        rebinds[path], container[path], qualified[path] = (
            _runtime_mutations(tree)
        )
        imports[path] = {
            (mod, local)
            for local, (mod, _sym) in symbols.from_imports[path].items()
        }

    for path in ctx.py_files:
        dotted = module_dotted(path)
        for name, (line, col, _sentinel) in sorted(
            candidates[path].items()
        ):
            hit = name in rebinds[path] or name in container[path]
            if not hit:
                # a sibling module that mutates the shared OBJECT —
                # through a module-qualified reference (mod.NAME[...])
                # or a container mutation of its from-imported name.
                # A sibling's `global NAME; NAME = ...` rebind of its
                # own imported copy does NOT blame this module
                for other in ctx.py_files:
                    if other == path:
                        continue
                    if (dotted, name) in qualified[other]:
                        hit = True
                        break
                    if name in container[other] and (
                        (dotted, name) in imports[other]
                    ):
                        hit = True
                        break
            if hit:
                out.append(Finding(
                    "GS601", path, line, col,
                    f"module-level mutable `{name}` is mutated at "
                    "runtime — forked pool workers silently share (or "
                    "silently don't share) its contents; make the "
                    "sharing decision explicit",
                    name,
                ))
    return out
