"""GS2xx — seed-stream registry rules (ISSUE 13).

The seed-split rule (PR 2): every stochastic process derives its own
independent stream as ``random.Random(f"{seed}:<namespace>")``, so
changing one knob's config never perturbs another stream's draws.  The
namespaces form a flat global space with no runtime collision check —
two processes picking the same namespace silently share a stream.  This
rule extracts every f-string handed to ``random.Random`` anywhere in
the package, normalizes the interpolation holes to ``{}``, and checks
the result against the declared registry
(``gpuschedule_tpu/lint/seed_registry.py``):

- **GS201** unregistered stream template,
- **GS202** registry row whose template is constructed nowhere (stale),
- **GS203** one template constructed at more than one call site
  (stream collision) unless declared in ``SHARED_SEED_STREAMS``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from gpuschedule_tpu.lint.core import Finding, LintContext, rule


def _template(js: ast.JoinedStr) -> str:
    parts: List[str] = []
    for v in js.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        else:
            parts.append("{}")
    return "".join(parts)


def _stream_sites(ctx: LintContext) -> List[Tuple[str, int, int, str]]:
    """(path, line, col, template) for every random.Random(f"...")."""
    sites = []
    for path in ctx.py_files:
        for node in ast.walk(ctx.tree(path)):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            is_random = (
                isinstance(fn, ast.Attribute) and fn.attr == "Random"
            ) or (isinstance(fn, ast.Name) and fn.id == "Random")
            if not is_random:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.JoinedStr):
                sites.append(
                    (path, node.lineno, node.col_offset, _template(arg))
                )
    return sites


@rule(codes=("GS201", "GS202", "GS203"))
def seed_stream_registry(ctx: LintContext) -> List[Finding]:
    registry_path = f"{ctx.config.package}/lint/seed_registry.py"
    if ctx.config.seed_streams is not None:
        registry: Dict[str, str] = dict(ctx.config.seed_streams)
        shared = set(ctx.config.shared_seed_streams)
        check_stale = True
    elif ctx.has(registry_path):
        # read the TARGET tree's declared registry statically (AST
        # literals, like the worldspec rule) — `lint --root OTHER`
        # must check OTHER's registry, not the running package's
        registry = {}
        shared = set()
        for node in ast.walk(ctx.tree(registry_path)):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id == "SEED_STREAMS" and isinstance(
                    node.value, ast.Dict
                ):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                            k.value, str
                        ):
                            registry[k.value] = ""
                elif t.id == "SHARED_SEED_STREAMS" and isinstance(
                    node.value, (ast.Tuple, ast.List)
                ):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, str
                        ):
                            shared.add(el.value)
        check_stale = True
    else:
        # a tree without the registry file: fall back to the running
        # package's registry for GS201, but never report stale rows —
        # they would all be stale against a fixture tree
        from gpuschedule_tpu.lint.seed_registry import (
            SEED_STREAMS,
            SHARED_SEED_STREAMS,
        )
        registry = dict(SEED_STREAMS)
        shared = set(SHARED_SEED_STREAMS)
        check_stale = False

    out: List[Finding] = []
    sites = _stream_sites(ctx)
    by_template: Dict[str, List[Tuple[str, int, int]]] = {}
    for path, line, col, tmpl in sites:
        by_template.setdefault(tmpl, []).append((path, line, col))
        if tmpl not in registry:
            out.append(Finding(
                "GS201", path, line, col,
                f"unregistered seed-stream namespace '{tmpl}': add it to "
                "lint/seed_registry.py (or it may collide silently)",
                tmpl,
            ))
    for tmpl, locs in sorted(by_template.items()):
        if len(locs) > 1 and tmpl not in shared:
            for path, line, col in locs[1:]:
                out.append(Finding(
                    "GS203", path, line, col,
                    f"seed-stream namespace '{tmpl}' is constructed at "
                    f"{len(locs)} call sites — two RNGs sharing one "
                    "namespace produce identical interleaved draw "
                    "sequences; declare it SHARED or pick a new namespace",
                    tmpl,
                ))
    # stale registry rows, anchored to the registry file's label
    for tmpl in sorted(registry):
        if check_stale and tmpl not in by_template:
            out.append(Finding(
                "GS202", registry_path, 0, 0,
                f"registered seed stream '{tmpl}' is constructed nowhere "
                "— remove the stale registry row",
                tmpl,
            ))
    return out
