"""GS7xx — state-machine conformance rules (ISSUE 14 tentpole).

The analyzer's transition table (``obs/analyze.py:_LEGAL_FROM``) is the
stream contract's armor: an event kind arriving while a job sits in a
state the table doesn't list is a hard ``StreamError`` (exit 2 on the
CLI).  PR 13 could not check it — the table's truth lives in *another
module*, in the engine's guard clauses and membership loops.  This rule
statically extracts both sides and cross-checks them in BOTH directions:

- **GS701** the engine can emit kind K for a job in state S but the
  analyzer rejects (K, S) — a future stream error waiting for the first
  replay that takes that path (also fired when the engine emits a
  per-job kind the table doesn't know at all);
- **GS702** the table allows (K, S) but no emit site can produce it —
  dead armor: readers build against transitions that cannot occur;
- **GS703** a per-job emit site whose job-state context the analysis
  cannot resolve — the pass refuses to guess; annotate the source.

Engine-side extraction walks every emitter module (LintConfig
``emitter_paths``) and infers the job state *before* the event applies
(state assignments are deliberately ignored — ``try_start`` flips the
job to RUNNING before emitting ``start``, but the analyzer transitions
on the event, so the *from*-state is the guarded entry state):

1. **guard clauses** — ``if job.state not in (PENDING, SUSPENDED):
   raise`` narrows ``job`` for everything after it, including ``or``
   guards ending in ``continue``/``return``/``raise``;
2. **membership provenance** — ``for job in self.running:`` and
   ``self.pending`` via the configured ``job_set_attrs`` map, through
   ``sorted``/``list`` wrappers, ternaries, and local rebinding;
3. **caller propagation** — a helper with no guard of its own
   (``_emit_rebind``, ``_finish``, ``_revoke``) inherits the union of
   its call sites' argument states, iterated to a fixed point over the
   module's call graph;
4. **annotations** — ``# lint: job-states[running]`` on a ``def`` (the
   function returns jobs in those states), an assignment, or a ``for``
   line, for provenance the analysis cannot reach (an indexed lookup,
   a dict of members).  States use the ANALYZER's vocabulary.

Engine ``JobState`` members map onto the analyzer's state names through
``LintConfig.state_aliases`` (``pending`` -> ``queued``).  Kinds the
analyzer consumes *before* its table lookup (``arrival``, ``reject``,
``fault``...) are extracted from the analyzer's own dispatch — the
``kind == "..."`` comparisons preceding the first ``_LEGAL_FROM``
reference — and exempted.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    const_str,
    rule,
)

_ANNOT_RE = re.compile(r"#\s*lint:\s*job-states\[([a-zA-Z_\-, ]+)\]")

# expression wrappers that preserve membership provenance
_PASSTHROUGH_CALLS = {"sorted", "list", "tuple", "reversed"}


def _annot_states(
    comments: Dict[int, str], line: int
) -> Optional[frozenset]:
    for ln in (line, line - 1):
        c = comments.get(ln)
        if c:
            m = _ANNOT_RE.search(c)
            if m:
                return frozenset(
                    s.strip() for s in m.group(1).split(",") if s.strip()
                )
    return None


# --------------------------------------------------------------------- #
# analyzer side: the _LEGAL_FROM table + pre-table kinds


def _module_str_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "str"`` bindings, including tuple unpacking
    (``QUEUED, RUNNING = "queued", "running"``)."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                s = const_str(node.value)
                if s is not None:
                    out[t.id] = s
            elif isinstance(t, ast.Tuple) and isinstance(
                node.value, ast.Tuple
            ) and len(t.elts) == len(node.value.elts):
                for el, v in zip(t.elts, node.value.elts):
                    s = const_str(v)
                    if isinstance(el, ast.Name) and s is not None:
                        out[el.id] = s
    return out


def _legal_from(
    tree: ast.Module, table_name: str
) -> Optional[Tuple[Dict[str, frozenset], Dict[str, int], int]]:
    """(kind -> allowed from-states, kind -> key line, table line)."""
    consts = _module_str_constants(tree)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == table_name
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        table: Dict[str, frozenset] = {}
        lines: Dict[str, int] = {}
        for k, v in zip(node.value.keys, node.value.values):
            kind = const_str(k) if k is not None else None
            if kind is None:
                continue
            states: Set[str] = set()
            if isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    s = const_str(el)
                    if s is None and isinstance(el, ast.Name):
                        s = consts.get(el.id)
                    if s is not None:
                        states.add(s)
            table[kind] = frozenset(states)
            lines[kind] = k.lineno
        return table, lines, node.lineno
    return None


def _pre_table_kinds(tree: ast.Module, table_name: str) -> Set[str]:
    """Kinds the analyzer dispatches on BEFORE its first table lookup:
    ``kind == "arrival"``-style comparisons with a lower line number
    than the first ``_LEGAL_FROM`` reference in the same function."""
    kinds: Set[str] = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        first_use: Optional[int] = None
        kind_vars: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == table_name:
                if first_use is None or node.lineno < first_use:
                    first_use = node.lineno
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == table_name
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                kind_vars.add(node.args[0].id)
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == table_name
                and isinstance(node.slice, ast.Name)
            ):
                kind_vars.add(node.slice.id)
        if first_use is None:
            continue
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Compare)
                and node.lineno < first_use
                and isinstance(node.left, ast.Name)
                and (not kind_vars or node.left.id in kind_vars)
                and len(node.ops) == 1
                and isinstance(node.ops[0], ast.Eq)
            ):
                s = const_str(node.comparators[0])
                if s is not None:
                    kinds.add(s)
    return kinds


# --------------------------------------------------------------------- #
# engine side: emit sites with inferred job-state context


@dataclass
class _Param:
    """Sentinel: context depends on this parameter of the enclosing
    function — resolved by caller propagation."""

    func: str
    name: str


@dataclass
class _EmitSite:
    kind: str
    path: str
    line: int
    col: int
    func: str
    context: object  # frozenset | _Param | None


@dataclass
class _CallSite:
    callee: str                       # local function/method name
    args: List[object] = field(default_factory=list)  # per-position context


class _FuncAnalysis:
    """One pass over a function body, statement order, tracking each
    name's possible job states."""

    def __init__(
        self,
        path: str,
        fname: str,
        states_map: Dict[str, str],      # JobState member -> analyzer state
        all_states: frozenset,
        job_sets: Dict[str, frozenset],  # self.<attr> -> states
        fn_returns: Dict[str, frozenset],  # annotated return states
        comments: Dict[int, str],
        params: Set[str],
        state_class: str,
    ):
        self.path = path
        self.fname = fname
        self.states_map = states_map
        self.all_states = all_states
        self.job_sets = job_sets
        self.fn_returns = fn_returns
        self.comments = comments
        self.params = params
        self.state_class = state_class
        self.emits: List[_EmitSite] = []
        self.calls: List[_CallSite] = []

    # -- state-test parsing ------------------------------------------- #

    def _state_const(self, node: ast.AST) -> Optional[str]:
        """``JobState.PENDING`` (or a bare enum-member constant string)
        -> the analyzer-vocabulary state name."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == self.state_class
        ):
            return self.states_map.get(node.attr)
        s = const_str(node)
        if s is not None:
            # a raw string compare against .state
            return self.states_map.get(s.upper(), s)
        return None

    def _state_test(
        self, test: ast.AST
    ) -> Optional[Tuple[str, frozenset, bool]]:
        """Parse ``X.state <op> ...`` -> (name, states, positive):
        ``positive`` True means the test passing implies state IN the
        set; False means the test passing implies state NOT IN it."""
        if not (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Attribute)
            and test.left.attr == "state"
            and isinstance(test.left.value, ast.Name)
            and len(test.ops) == 1
        ):
            return None
        name = test.left.value.id
        op = test.ops[0]
        comp = test.comparators[0]
        states: Set[str] = set()
        if isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
            for el in comp.elts:
                s = self._state_const(el)
                if s is None:
                    return None
                states.add(s)
        else:
            s = self._state_const(comp)
            if s is None:
                return None
            states.add(s)
        if isinstance(op, (ast.In, ast.Is, ast.Eq)):
            return name, frozenset(states), True
        if isinstance(op, (ast.NotIn, ast.IsNot, ast.NotEq)):
            return name, frozenset(states), False
        return None

    def _narrow_reject(self, test: ast.AST, env: Dict[str, object]) -> None:
        """The guard's body is terminal, so AFTER the If the test is
        known false — apply the negated narrowing.  ``or`` guards
        narrow by every state conjunct (all disjuncts are false)."""
        tests = (
            test.values if isinstance(test, ast.BoolOp)
            and isinstance(test.op, ast.Or) else [test]
        )
        for t in tests:
            parsed = self._state_test(t)
            if parsed is None:
                continue
            name, states, positive = parsed
            if positive:
                # test was `state in S` and it is false -> state not in S
                cur = env.get(name)
                base = cur if isinstance(cur, frozenset) else self.all_states
                env[name] = base - states
            else:
                # test was `state not in S` and it is false -> state in S
                cur = env.get(name)
                if isinstance(cur, frozenset):
                    env[name] = cur & states
                else:
                    env[name] = states

    def _narrow_positive(self, test: ast.AST, env: Dict[str, object]) -> None:
        """Inside an If body: the test is known true."""
        tests = (
            test.values if isinstance(test, ast.BoolOp)
            and isinstance(test.op, ast.And) else [test]
        )
        for t in tests:
            parsed = self._state_test(t)
            if parsed is None:
                continue
            name, states, positive = parsed
            cur = env.get(name)
            if positive:
                if isinstance(cur, frozenset):
                    env[name] = cur & states
                else:
                    env[name] = states
            else:
                base = cur if isinstance(cur, frozenset) else self.all_states
                env[name] = base - states

    # -- expression provenance ---------------------------------------- #

    def _states_of(self, node: ast.AST, env: Dict[str, object]) -> object:
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in self.params:
                return _Param(self.fname, node.id)
            return None
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return self.job_sets.get(node.attr)
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Name)
                and f.id in _PASSTHROUGH_CALLS
                and node.args
            ):
                return self._states_of(node.args[0], env)
            callee = None
            if isinstance(f, ast.Name):
                callee = f.id
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
            ):
                callee = f.attr
            if callee is not None and callee in self.fn_returns:
                return self.fn_returns[callee]
            return None
        if isinstance(node, ast.IfExp):
            a = self._states_of(node.body, env)
            b = self._states_of(node.orelse, env)
            if isinstance(a, frozenset) and isinstance(b, frozenset):
                return a | b
            return None
        return None

    # -- statement walk ----------------------------------------------- #

    @staticmethod
    def _terminal(body: List[ast.stmt]) -> bool:
        return bool(body) and isinstance(
            body[-1], (ast.Raise, ast.Return, ast.Continue, ast.Break)
        )

    def walk(self, body: List[ast.stmt], env: Dict[str, object]) -> None:
        for stmt in body:
            ann = _annot_states(self.comments, stmt.lineno)
            if isinstance(stmt, ast.Assign):
                self._scan_exprs(stmt.value, env)
                states = ann if ann is not None else self._states_of(
                    stmt.value, env
                )
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        env[t.id] = states
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_exprs(stmt.iter, env)
                states = ann if ann is not None else self._states_of(
                    stmt.iter, env
                )
                inner = dict(env)
                if isinstance(stmt.target, ast.Name):
                    inner[stmt.target.id] = states
                self.walk(stmt.body, inner)
                self.walk(stmt.orelse, dict(env))
            elif isinstance(stmt, ast.If):
                self._scan_exprs(stmt.test, env)
                body_env = dict(env)
                self._narrow_positive(stmt.test, body_env)
                self.walk(stmt.body, body_env)
                else_env = dict(env)
                self._narrow_reject(stmt.test, else_env)
                self.walk(stmt.orelse, else_env)
                if self._terminal(stmt.body):
                    # the guard pattern: code after the If sees the
                    # negated test
                    self._narrow_reject(stmt.test, env)
            elif isinstance(stmt, (ast.While,)):
                self._scan_exprs(stmt.test, env)
                self.walk(stmt.body, dict(env))
                self.walk(stmt.orelse, dict(env))
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._scan_exprs(item.context_expr, env)
                self.walk(stmt.body, env)
            elif isinstance(stmt, ast.Try):
                self.walk(stmt.body, dict(env))
                for h in stmt.handlers:
                    self.walk(h.body, dict(env))
                self.walk(stmt.orelse, dict(env))
                self.walk(stmt.finalbody, dict(env))
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # nested scopes: out of scope for this pass
            else:
                self._scan_exprs(stmt, env)

    def _scan_exprs(self, node: ast.AST, env: Dict[str, object]) -> None:
        """Record emit sites and propagation-relevant call sites in this
        expression tree."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr == "event" and sub.args:
                kind = const_str(sub.args[0])
                if kind is None:
                    continue
                jobarg = sub.args[2] if len(sub.args) >= 3 else None
                if jobarg is None:
                    for kw in sub.keywords:
                        if kw.arg == "job":
                            jobarg = kw.value
                if jobarg is None or (
                    isinstance(jobarg, ast.Constant)
                    and jobarg.value is None
                ):
                    continue  # cluster-level record: no job at all
                self.emits.append(_EmitSite(
                    kind, self.path, sub.lineno, sub.col_offset,
                    self.fname, self._states_of(jobarg, env),
                ))
                continue
            callee = None
            if isinstance(f, ast.Name):
                callee = f.id
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
            ):
                callee = f.attr
            if callee is None:
                continue
            args = [self._states_of(a, env) for a in sub.args]
            if any(a is not None for a in args):
                self.calls.append(_CallSite(callee, args))


def _jobstate_map(
    tree: ast.Module, class_name: str, aliases: Dict[str, str]
) -> Dict[str, str]:
    """JobState member name -> analyzer-vocabulary state string."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for sub in node.body:
                if isinstance(sub, ast.Assign):
                    s = const_str(sub.value)
                    for t in sub.targets:
                        if isinstance(t, ast.Name) and s is not None:
                            out[t.id] = aliases.get(s, s)
    return out


def _analyze_emitter(
    ctx: LintContext,
    path: str,
    states_map: Dict[str, str],
    all_states: frozenset,
) -> List[_EmitSite]:
    """All per-job emit sites of one module, contexts resolved through
    the in-module call graph to a fixed point."""
    cfg = ctx.config
    tree = ctx.tree(path)
    comments = ctx.comments(path)
    job_sets = {
        attr: frozenset(states) for attr, states in cfg.job_set_attrs
    }
    fn_returns: Dict[str, frozenset] = {}
    funcs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)
            ann = _annot_states(comments, node.lineno)
            if ann is not None:
                fn_returns[node.name] = ann

    analyses: Dict[str, _FuncAnalysis] = {}
    for name, fn in funcs.items():
        a = fn.args
        params = {
            arg.arg
            for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs,
                        a.vararg, a.kwarg)
            if arg is not None and arg.arg != "self"
        }
        fa = _FuncAnalysis(
            path, name, states_map, all_states, job_sets, fn_returns,
            comments, params, cfg.job_state_class,
        )
        fa.walk(fn.body, {})
        analyses[name] = fa

    # caller propagation: param -> union of known arg states across all
    # call sites, iterated to a fixed point (the call graph is small)
    param_states: Dict[Tuple[str, str], frozenset] = {}
    for _ in range(len(funcs) + 2):
        changed = False
        for fa in analyses.values():
            for call in fa.calls:
                callee = funcs.get(call.callee)
                if callee is None:
                    continue
                a = callee.args
                names = [arg.arg for arg in (*a.posonlyargs, *a.args)]
                if names and names[0] == "self":
                    names = names[1:]
                for pos, context in enumerate(call.args):
                    if pos >= len(names):
                        break
                    if isinstance(context, _Param):
                        context = param_states.get(
                            (context.func, context.name)
                        )
                    if not isinstance(context, frozenset):
                        continue
                    key = (call.callee, names[pos])
                    cur = param_states.get(key, frozenset())
                    new = cur | context
                    if new != cur:
                        param_states[key] = new
                        changed = True
        if not changed:
            break

    out: List[_EmitSite] = []
    for fa in analyses.values():
        for site in fa.emits:
            if isinstance(site.context, _Param):
                site.context = param_states.get(
                    (site.context.func, site.context.name)
                )
            out.append(site)
    return out


@rule(codes=("GS701", "GS702", "GS703"))
def state_machine_conformance(ctx: LintContext) -> List[Finding]:
    cfg = ctx.config
    if not ctx.has(cfg.analyzer_path) or not ctx.has(cfg.job_state_path):
        return []
    parsed = _legal_from(ctx.tree(cfg.analyzer_path), cfg.legal_from_name)
    if parsed is None:
        return []
    table, key_lines, table_line = parsed
    pre_table = _pre_table_kinds(ctx.tree(cfg.analyzer_path),
                                 cfg.legal_from_name)
    aliases = dict(cfg.state_aliases)
    states_map = _jobstate_map(
        ctx.tree(cfg.job_state_path), cfg.job_state_class, aliases
    )
    analyzer_states = frozenset().union(*table.values()) if table else frozenset()

    sites: List[_EmitSite] = []
    for path in cfg.emitter_paths:
        if ctx.has(path):
            sites.extend(
                _analyze_emitter(ctx, path, states_map, analyzer_states)
            )

    out: List[Finding] = []
    by_kind: Dict[str, List[_EmitSite]] = {}
    for s in sites:
        by_kind.setdefault(s.kind, []).append(s)

    flagged_unknown: Set[str] = set()
    for s in sorted(sites, key=lambda s: (s.path, s.line, s.col)):
        if s.kind in pre_table:
            continue  # consumed before the transition table
        if s.kind not in table:
            if s.kind not in flagged_unknown:
                flagged_unknown.add(s.kind)
                out.append(Finding(
                    "GS701", s.path, s.line, s.col,
                    f"engine emits per-job kind '{s.kind}' that "
                    f"{cfg.analyzer_path}:{cfg.legal_from_name} has no "
                    "transition rule for — the analyzer will reject the "
                    "stream",
                    f"kind:{s.kind}",
                ))
            continue
        if not isinstance(s.context, frozenset):
            out.append(Finding(
                "GS703", s.path, s.line, s.col,
                f"cannot infer the job-state context of this '{s.kind}' "
                "emit site — add a guard the pass can read or a "
                "`# lint: job-states[...]` annotation "
                "(docs/static-analysis.md)",
                f"{s.kind}@{s.func}",
            ))
            continue
        for state in sorted(s.context - table[s.kind]):
            out.append(Finding(
                "GS701", s.path, s.line, s.col,
                f"engine can emit '{s.kind}' for a job in state "
                f"'{state}' but {cfg.legal_from_name} only allows "
                f"{sorted(table[s.kind])} — a replay taking this path "
                "is a stream error",
                f"{s.kind}:{state}",
            ))

    for kind in sorted(table):
        kind_sites = by_kind.get(kind, [])
        if not kind_sites:
            out.append(Finding(
                "GS702", cfg.analyzer_path,
                key_lines.get(kind, table_line), 0,
                f"{cfg.legal_from_name} has a transition rule for "
                f"'{kind}' but no emitter produces that kind — dead "
                "armor (or a missing emitter config row)",
                f"kind:{kind}",
            ))
            continue
        if not all(isinstance(s.context, frozenset) for s in kind_sites):
            continue  # unresolved site already flagged; can't prove dead
        produced = frozenset().union(*(s.context for s in kind_sites))
        for state in sorted(table[kind] - produced):
            out.append(Finding(
                "GS702", cfg.analyzer_path,
                key_lines.get(kind, table_line), 0,
                f"{cfg.legal_from_name} allows '{kind}' from state "
                f"'{state}' but no emit site can produce it — dead "
                "armor the engine's state machine contradicts",
                f"{kind}:{state}",
            ))
    return out
