"""Contract linter (ISSUE 13/14): AST-enforced determinism, seed-stream,
schema, config-hash, cache-discipline, fork-safety, and state-machine
invariants — a whole-program pass over the package's own ASTs, built on
the shared symbol table / call graph in ``lint/symbols.py``.

Entry points: ``run_lint(root)`` (Python), ``python -m gpuschedule_tpu
lint`` (CLI, ``--update-baseline`` rewrites the baseline), and
``tools/contract_lint.py`` (CI gate with a wall-time budget).  Rule
catalog and suppression workflow: docs/static-analysis.md.
"""

from gpuschedule_tpu.lint.core import (
    Finding,
    LintConfig,
    LintContext,
    LintReport,
    load_baseline,
    registered_codes,
    run_lint,
)
from gpuschedule_tpu.lint.seed_registry import (
    SEED_STREAMS,
    SHARED_SEED_STREAMS,
)
from gpuschedule_tpu.lint.symbols import SymbolTable

__all__ = [
    "Finding",
    "LintConfig",
    "LintContext",
    "LintReport",
    "SEED_STREAMS",
    "SHARED_SEED_STREAMS",
    "SymbolTable",
    "load_baseline",
    "registered_codes",
    "run_lint",
]
