"""Contract linter (ISSUE 13): AST-enforced determinism, seed-stream,
schema, config-hash, cache-discipline, and fork-safety invariants.

Entry points: ``run_lint(root)`` (Python), ``python -m gpuschedule_tpu
lint`` (CLI), ``tools/contract_lint.py`` (CI gate).  Rule catalog and
suppression workflow: docs/static-analysis.md.
"""

from gpuschedule_tpu.lint.core import (
    Finding,
    LintConfig,
    LintContext,
    LintReport,
    load_baseline,
    run_lint,
)
from gpuschedule_tpu.lint.seed_registry import (
    SEED_STREAMS,
    SHARED_SEED_STREAMS,
)

__all__ = [
    "Finding",
    "LintConfig",
    "LintContext",
    "LintReport",
    "SEED_STREAMS",
    "SHARED_SEED_STREAMS",
    "load_baseline",
    "run_lint",
]
