"""GS1xx — determinism rules (ISSUE 13).

The engine's first contract (PR 2 onward): a seeded replay is a pure
function of its config.  Inside the replay-semantics modules
(``sim/``, ``net/``, ``faults/``, ``cluster/``) that forbids:

- **GS101** wall-clock reads (``time.time``/``perf_counter``/
  ``datetime.now``...): wall time changes between runs, so any value
  derived from it breaks byte-identical replay.  The obs layer
  (tracer, selfprof) is *outside* these dirs — wall time is its job;
  in-scope measurement sites (the self-profiler loop, what-if latency,
  worker-pool timeouts) carry reasoned pragmas or baseline rows.
- **GS102** module-state RNG (``random.shuffle``, ``np.random.rand``):
  global RNG state is shared across every caller in the process, so
  draws interleave unpredictably; the seed-split rule requires a
  namespaced ``random.Random(...)`` instance instead.
- **GS103** bare-set iteration: set order is hash-randomized across
  processes (PYTHONHASHSEED), so iterating one to emit events or order
  flows is a fork/worker-dependent replay.  Wrap in ``sorted(...)`` or
  keep an ordered structure.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    dotted_name,
    import_aliases,
    rule,
)

WALLCLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.process_time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# seeded constructors are the *sanctioned* RNG surface; everything else
# reachable under the random / numpy.random module roots is module state
_RNG_OK_LEAVES = {"Random", "SystemRandom", "default_rng", "Generator",
                  "RandomState", "Philox", "PCG64", "SFC64", "MT19937",
                  "SeedSequence", "BitGenerator"}


def _target_files(ctx: LintContext) -> List[str]:
    dirs = tuple(
        f"{ctx.config.package}/{d}/" for d in ctx.config.determinism_dirs
    )
    return [p for p in ctx.py_files if p.startswith(dirs)]


def _rng_violation(name: str) -> bool:
    parts = name.split(".")
    if parts[0] == "random":
        return len(parts) > 1 and parts[-1] not in _RNG_OK_LEAVES
    if parts[0] in ("numpy", "np") and len(parts) > 2 and parts[1] == "random":
        return parts[-1] not in _RNG_OK_LEAVES
    return False


@rule
def wallclock_and_module_rng(ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    for path in _target_files(ctx):
        tree = ctx.tree(path)
        aliases = import_aliases(tree)
        for node in ast.walk(tree):
            # flag the *reference* (Attribute chain or from-imported
            # Name), not just calls: `perf = time.perf_counter` aliases
            # the clock and must be caught at the aliasing site
            if isinstance(node, ast.Attribute):
                if not isinstance(getattr(node, "ctx", None), ast.Load):
                    continue
                name = dotted_name(node, aliases)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                name = aliases.get(node.id)
                # only from-imports resolve to dotted leaves; a bare
                # `import time` Name reference is the Attribute case
                if name is not None and "." not in name:
                    name = None
            else:
                continue
            if name is None:
                continue
            # skip inner Attribute nodes of a longer flagged chain:
            # datetime.datetime.now flags once, at the full chain
            if name in WALLCLOCK:
                out.append(Finding(
                    "GS101", path, node.lineno, node.col_offset,
                    f"wall-clock read `{name}` inside a replay-semantics "
                    "module breaks deterministic replay",
                    name,
                ))
            elif _rng_violation(name):
                out.append(Finding(
                    "GS102", path, node.lineno, node.col_offset,
                    f"module-state RNG `{name}` shares global stream "
                    "state; use a namespaced random.Random instance "
                    "(seed-split rule)",
                    name,
                ))
    return _dedup_chain(out)


def _dedup_chain(findings: List[Finding]) -> List[Finding]:
    """An Attribute chain like ``datetime.datetime.now`` resolves at two
    depths (`datetime.datetime.now` and nothing else matching) — but a
    call also visits the chain as the Call's func child, producing one
    finding per matching node at the same location.  Collapse exact
    (code, path, line, col, detail) duplicates."""
    seen: Set[tuple] = set()
    out: List[Finding] = []
    for f in findings:
        k = (f.code, f.path, f.line, f.col, f.detail)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def _is_setish(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("set", "frozenset")
    return False


class _SetIterVisitor(ast.NodeVisitor):
    """Per-function tracking: names locally bound to set expressions,
    plus ``self.<attr>`` names bound to sets anywhere in the enclosing
    class.  Iterating either (outside ``sorted(...)``) is a finding."""

    def __init__(self, path: str, class_set_attrs: Set[str]):
        self.path = path
        self.class_set_attrs = class_set_attrs
        self.local_sets: Set[str] = set()
        self.findings: List[Finding] = []

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_setish(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.local_sets.add(t.id)
        else:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.local_sets.discard(t.id)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # annotated bindings (`s: Set[int] = set()`) track the same way
        if isinstance(node.target, ast.Name) and node.value is not None:
            if _is_setish(node.value):
                self.local_sets.add(node.target.id)
            else:
                self.local_sets.discard(node.target.id)
        self.generic_visit(node)

    def _check_iter(self, it: ast.AST) -> None:
        bad: Optional[str] = None
        if _is_setish(it):
            bad = "set-literal"
        elif isinstance(it, ast.Name) and it.id in self.local_sets:
            bad = it.id
        elif (
            isinstance(it, ast.Attribute)
            and isinstance(it.value, ast.Name)
            and it.value.id == "self"
            and it.attr in self.class_set_attrs
        ):
            bad = f"self.{it.attr}"
        if bad is not None:
            self.findings.append(Finding(
                "GS103", self.path, it.lineno, it.col_offset,
                f"iteration over bare set `{bad}`: set order is "
                "hash-randomized across processes — sort it or keep an "
                "ordered structure",
                bad,
            ))

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)

    def _visit_comp(self, node) -> None:
        for gen in node.generators:
            self._check_iter(gen.iter)
        self.generic_visit(node)

    visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp
    visit_DictComp = _visit_comp


def _class_set_attrs(cls: ast.ClassDef) -> Set[str]:
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        targets: list = []
        if isinstance(node, ast.Assign) and _is_setish(node.value):
            targets = node.targets
        elif (
            isinstance(node, ast.AnnAssign)
            and node.value is not None
            and _is_setish(node.value)
        ):
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                attrs.add(t.attr)
    return attrs


@rule
def bare_set_iteration(ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    for path in _target_files(ctx):
        tree = ctx.tree(path)

        def scan(node: ast.AST, attrs: Set[str]) -> None:
            # generic descent (if/try/with wrappers included) swapping
            # the self-attr set at class boundaries and visiting each
            # function body once at its outermost def (nested defs are
            # walked by the visitor itself)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan(child, _class_set_attrs(child))
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    v = _SetIterVisitor(path, attrs)
                    for stmt in child.body:
                        v.visit(stmt)
                    out.extend(v.findings)
                else:
                    scan(child, attrs)

        scan(tree, set())
    return out
