"""GS1xx — determinism rules (ISSUE 13).

The engine's first contract (PR 2 onward): a seeded replay is a pure
function of its config.  Inside the replay-semantics modules
(``sim/``, ``net/``, ``faults/``, ``cluster/``) that forbids:

- **GS101** wall-clock reads (``time.time``/``perf_counter``/
  ``datetime.now``...): wall time changes between runs, so any value
  derived from it breaks byte-identical replay.  The obs layer
  (tracer, selfprof) is *outside* these dirs — wall time is its job;
  in-scope measurement sites (the self-profiler loop, what-if latency,
  worker-pool timeouts) carry reasoned pragmas or baseline rows.
- **GS102** module-state RNG (``random.shuffle``, ``np.random.rand``):
  global RNG state is shared across every caller in the process, so
  draws interleave unpredictably; the seed-split rule requires a
  namespaced ``random.Random(...)`` instance instead.
- **GS103** bare-set iteration: set order is hash-randomized across
  processes (PYTHONHASHSEED), so iterating one to emit events or order
  flows is a fork/worker-dependent replay.  Wrap in ``sorted(...)`` or
  keep an ordered structure.

  ISSUE 14: detection is whole-program via the package symbol table
  (lint/symbols.py) — a set built in ``cluster/base.py`` and iterated
  in ``sim/engine.py`` resolves through from-imports, set-returning
  functions/methods, and class-attribute provenance, not just local
  bindings of the iterating function.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    dotted_name,
    import_aliases,
    rule,
)

WALLCLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "time.process_time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

# seeded constructors are the *sanctioned* RNG surface; everything else
# reachable under the random / numpy.random module roots is module state
_RNG_OK_LEAVES = {"Random", "SystemRandom", "default_rng", "Generator",
                  "RandomState", "Philox", "PCG64", "SFC64", "MT19937",
                  "SeedSequence", "BitGenerator"}


def _target_files(ctx: LintContext) -> List[str]:
    dirs = tuple(
        f"{ctx.config.package}/{d}/" for d in ctx.config.determinism_dirs
    )
    extras = set(getattr(ctx.config, "determinism_files", ()))
    return [p for p in ctx.py_files if p.startswith(dirs) or p in extras]


def _rng_violation(name: str) -> bool:
    parts = name.split(".")
    if parts[0] == "random":
        return len(parts) > 1 and parts[-1] not in _RNG_OK_LEAVES
    if parts[0] in ("numpy", "np") and len(parts) > 2 and parts[1] == "random":
        return parts[-1] not in _RNG_OK_LEAVES
    return False


@rule(codes=("GS101", "GS102"))
def wallclock_and_module_rng(ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    for path in _target_files(ctx):
        tree = ctx.tree(path)
        aliases = import_aliases(tree)
        for node in ast.walk(tree):
            # flag the *reference* (Attribute chain or from-imported
            # Name), not just calls: `perf = time.perf_counter` aliases
            # the clock and must be caught at the aliasing site
            if isinstance(node, ast.Attribute):
                if not isinstance(getattr(node, "ctx", None), ast.Load):
                    continue
                name = dotted_name(node, aliases)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                name = aliases.get(node.id)
                # only from-imports resolve to dotted leaves; a bare
                # `import time` Name reference is the Attribute case
                if name is not None and "." not in name:
                    name = None
            else:
                continue
            if name is None:
                continue
            # skip inner Attribute nodes of a longer flagged chain:
            # datetime.datetime.now flags once, at the full chain
            if name in WALLCLOCK:
                out.append(Finding(
                    "GS101", path, node.lineno, node.col_offset,
                    f"wall-clock read `{name}` inside a replay-semantics "
                    "module breaks deterministic replay",
                    name,
                ))
            elif _rng_violation(name):
                out.append(Finding(
                    "GS102", path, node.lineno, node.col_offset,
                    f"module-state RNG `{name}` shares global stream "
                    "state; use a namespaced random.Random instance "
                    "(seed-split rule)",
                    name,
                ))
    return _dedup_chain(out)


def _dedup_chain(findings: List[Finding]) -> List[Finding]:
    """An Attribute chain like ``datetime.datetime.now`` resolves at two
    depths (`datetime.datetime.now` and nothing else matching) — but a
    call also visits the chain as the Call's func child, producing one
    finding per matching node at the same location.  Collapse exact
    (code, path, line, col, detail) duplicates."""
    seen: Set[tuple] = set()
    out: List[Finding] = []
    for f in findings:
        k = (f.code, f.path, f.line, f.col, f.detail)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def _iter_label(it: ast.AST) -> str:
    """Stable fingerprint for the iterated expression."""
    if isinstance(it, ast.Name):
        return it.id
    if isinstance(it, ast.Attribute) and isinstance(it.value, ast.Name):
        return f"{it.value.id}.{it.attr}"
    if isinstance(it, ast.Call):
        f = it.func
        if isinstance(f, ast.Name):
            return f"{f.id}()"
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            return f"{f.value.id}.{f.attr}()"
        return "call()"
    return "set-literal"


class _SetIterVisitor(ast.NodeVisitor):
    """Per-function tracking: names locally bound (or provably NOT
    bound) to sets, layered over the package symbol table's
    whole-program provenance — module-level sets reached through
    from-imports, set-returning functions/methods, and class-attribute
    assignment (ISSUE 14).  Iterating any provable set outside
    ``sorted(...)`` is a finding."""

    def __init__(self, path: str, cls: Optional[str], symbols,
                 nonsets: Optional[Set[str]] = None):
        self.path = path
        self.cls = cls
        self.symbols = symbols
        self.local_sets: Set[str] = set()
        # params / loop / with / comprehension targets pre-seed as
        # NON-sets: a binding shadowing a module-level set must never be
        # misread as it (assignments below may still flip it to a set)
        self.local_nonsets: Set[str] = set(nonsets or ())
        self.findings: List[Finding] = []

    def _is_setish(self, node: ast.AST) -> bool:
        return self.symbols.expr_is_set(
            self.path, self.cls, node, self.local_sets, self.local_nonsets
        )

    def _bind(self, name: str, is_set: bool) -> None:
        if is_set:
            self.local_sets.add(name)
            self.local_nonsets.discard(name)
        else:
            self.local_nonsets.add(name)
            self.local_sets.discard(name)

    def visit_Assign(self, node: ast.Assign) -> None:
        is_set = self._is_setish(node.value)
        for t in node.targets:
            if isinstance(t, ast.Name):
                self._bind(t.id, is_set)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # annotated bindings (`s: Set[int] = set()`) track the same way
        if isinstance(node.target, ast.Name) and node.value is not None:
            self._bind(node.target.id, self._is_setish(node.value))
        self.generic_visit(node)

    def _check_iter(self, it: ast.AST) -> None:
        if self._is_setish(it):
            bad = _iter_label(it)
            self.findings.append(Finding(
                "GS103", self.path, it.lineno, it.col_offset,
                f"iteration over bare set `{bad}`: set order is "
                "hash-randomized across processes — sort it or keep an "
                "ordered structure",
                bad,
            ))

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)

    def _visit_comp(self, node) -> None:
        for gen in node.generators:
            self._check_iter(gen.iter)
        self.generic_visit(node)

    visit_ListComp = visit_SetComp = visit_GeneratorExp = _visit_comp
    visit_DictComp = _visit_comp


@rule(codes=("GS103",))
def bare_set_iteration(ctx: LintContext) -> List[Finding]:
    from gpuschedule_tpu.lint.symbols import bound_names

    symbols = ctx.symbols()
    out: List[Finding] = []
    for path in _target_files(ctx):
        tree = ctx.tree(path)

        def scan(node: ast.AST, cls: Optional[str]) -> None:
            # generic descent (if/try/with wrappers included) swapping
            # the enclosing class at class boundaries and visiting each
            # function body once at its outermost def (nested defs are
            # walked by the visitor itself)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan(child, child.name)
                elif isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    v = _SetIterVisitor(path, cls, symbols,
                                        nonsets=bound_names(child))
                    for stmt in child.body:
                        v.visit(stmt)
                    out.extend(v.findings)
                else:
                    scan(child, cls)

        scan(tree, None)
    return out
