"""GS3xx — event-schema drift rules (ISSUE 13).

``docs/events.md`` is the contract the analytics layer, the Perfetto
exporter, and every external consumer of the event stream read against;
``sim/engine.py`` is the only writer.  Schema v1 is additive-only, so
drift has exactly two shapes, both statically detectable:

- **GS301** the engine emits an event kind the document doesn't list
  (an undocumented record every reader must guess at);
- **GS302** the document lists a kind the engine never emits (dead
  documentation that readers build against);
- **GS303** the engine emits a payload key that appears nowhere in the
  document (an undocumented field).

Extraction: every ``*.event("<kind>", t, job, key=..., **extra)`` call
in the engine — explicit keywords plus the keys of any local ``extra``
dict the call splats (dict literals and ``extra["k"] = ...`` stores in
the enclosing function are resolved; opaque splats like
``**cluster.sample_state()`` contribute nothing, which is safe because
GS303 only checks the *extracted* keys).  The document side parses the
markdown tables whose header column is ``kind``; payload keys match
against every backticked token in the document (tables and prose — the
shared ``slow_factor``/``why``/``blame`` semantics live in prose).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    backtick_tokens,
    const_str,
    rule,
)


def _doc_kinds(text: str) -> Set[str]:
    """The documented event kinds: first-column backtick tokens of every
    markdown table whose header's first column is ``kind``.  (Payload
    keys match against the whole document's tokens, not per-row — the
    shared ``slow_factor``/``why``/``blame`` semantics live in prose.)"""
    kinds: Set[str] = set()
    in_table = False
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "kind":
            in_table = True
            continue
        if set(cells[0]) <= {"-", ":", " "}:
            continue
        if not in_table:
            continue
        m = re.fullmatch(r"`([^`]+)`", cells[0])
        if m:
            kinds.add(m.group(1))
        else:
            # a non-backticked first cell is a different table's header
            # (e.g. `| cache | count |` adjacent with no blank line) —
            # stop collecting so its rows aren't read as event kinds
            in_table = False
    return kinds


class _ExtraResolver(ast.NodeVisitor):
    """Collect, per function, the constant keys flowing into each local
    name that is later ``**``-splatted: dict-literal assignments and
    ``name["key"] = ...`` subscript stores."""

    def __init__(self) -> None:
        self.keys: Dict[str, Set[str]] = {}
        self.opaque: Set[str] = set()

    def _add_dict(self, name: str, d: ast.Dict) -> None:
        bucket = self.keys.setdefault(name, set())
        for k in d.keys:
            s = const_str(k) if k is not None else None
            if s is None:
                self.opaque.add(name)
            else:
                bucket.add(s)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name):
                if isinstance(node.value, ast.Dict):
                    self._add_dict(t.id, node.value)
                else:
                    self.opaque.add(t.id)
            elif (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
            ):
                key = const_str(t.slice)
                if key is None:
                    self.opaque.add(t.value.id)
                else:
                    self.keys.setdefault(t.value.id, set()).add(key)
        self.generic_visit(node)


def _emitted(tree: ast.AST) -> Dict[str, List[Tuple[int, int, Set[str]]]]:
    """kind -> [(line, col, payload keys)] for every ``.event("kind",
    ...)`` call, with local ``extra`` splats resolved per function."""
    out: Dict[str, List[Tuple[int, int, Set[str]]]] = {}
    funcs: List[ast.AST] = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in funcs:
        resolver = _ExtraResolver()
        resolver.visit(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr == "event"):
                continue
            if not node.args:
                continue
            kind = const_str(node.args[0])
            if kind is None:
                continue
            keys: Set[str] = set()
            for kw in node.keywords:
                if kw.arg is not None:
                    keys.add(kw.arg)
                elif isinstance(kw.value, ast.Name):
                    name = kw.value.id
                    keys |= resolver.keys.get(name, set())
                # non-Name splats (**obj.method()) are opaque: skip
            out.setdefault(kind, []).append(
                (node.lineno, node.col_offset, keys)
            )
    return out


@rule
def event_schema_drift(ctx: LintContext) -> List[Finding]:
    cfg = ctx.config
    if not ctx.has(cfg.engine_path) or not ctx.has(cfg.events_doc_path):
        return []
    doc_text = ctx.source(cfg.events_doc_path)
    doc_kinds = _doc_kinds(doc_text)
    doc_tokens = backtick_tokens(doc_text)
    emitted = _emitted(ctx.tree(cfg.engine_path))

    out: List[Finding] = []
    for kind in sorted(emitted):
        line, col, _ = emitted[kind][0]
        if kind not in doc_kinds:
            out.append(Finding(
                "GS301", cfg.engine_path, line, col,
                f"engine emits event kind '{kind}' that "
                f"{cfg.events_doc_path} does not document",
                f"kind:{kind}",
            ))
    for kind in sorted(doc_kinds):
        if kind not in emitted:
            out.append(Finding(
                "GS302", cfg.events_doc_path, 0, 0,
                f"{cfg.events_doc_path} documents event kind '{kind}' "
                f"that {cfg.engine_path} never emits",
                f"kind:{kind}",
            ))
    seen: Set[Tuple[str, str]] = set()
    for kind in sorted(emitted):
        for line, col, keys in emitted[kind]:
            for key in sorted(keys):
                if key in doc_tokens or (kind, key) in seen:
                    continue
                seen.add((kind, key))
                out.append(Finding(
                    "GS303", cfg.engine_path, line, col,
                    f"event '{kind}' payload key '{key}' appears nowhere "
                    f"in {cfg.events_doc_path}",
                    f"key:{kind}.{key}",
                ))
    return out
