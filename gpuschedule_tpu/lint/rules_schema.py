"""GS3xx — event-schema drift rules (ISSUE 13, precision ISSUE 14).

``docs/events.md`` is the contract the analytics layer, the Perfetto
exporter, and every external consumer of the event stream read against;
the emitters are the modules listed in ``LintConfig.emitter_paths``
(``sim/engine.py`` today joined by ``sim/whatif.py`` and
``sim/snapshot.py`` — a second emitter growing an event site is linted
from day one).  Schema v1 is additive-only, so drift has these shapes,
all statically detectable:

- **GS301** an emitter emits an event kind the document doesn't list
  (an undocumented record every reader must guess at);
- **GS302** the document lists a kind no emitter ever emits (dead
  documentation that readers build against);
- **GS303** an emitted payload key absent from ITS KIND's payload cell
  in the document — per-kind, not document-wide (ISSUE 14): a key
  documented for ``start`` no longer covers the same key smuggled onto
  ``finish``;
- **GS304** a payload key documented in a kind's cell that no emit
  site for that kind produces — dead per-kind documentation.  Only
  enforced for kinds whose every emit site is fully resolvable (a
  ``**dynamic`` splat the resolver cannot see suppresses the check for
  that kind, never invents a finding), and only for cell tokens that
  are live payload keys of SOME kind — prose tokens, outcome enums,
  and cache names inside a cell never false-positive.

Extraction: every ``*.event("<kind>", t, job, key=..., **extra)`` call
in an emitter — explicit keywords plus the keys of any local ``extra``
dict the call splats (dict literals, ``extra["k"] = ...`` stores, and
``extra.update({...})`` literal merges in the enclosing function are
resolved; opaque splats like ``**cluster.sample_state()`` or
``.update(param)`` mark the site opaque).  The document side parses the
markdown tables whose header column is ``kind``: a kind's documented
payload keys are the backticked tokens of its OWN row (payload +
transition cells), so shared keys (``slow_factor``, ``blame``,
``cause``) must be named in every row that carries them.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import (
    Finding,
    LintContext,
    backtick_tokens,
    const_str,
    rule,
)


def _doc_kind_rows(text: str) -> Dict[str, Set[str]]:
    """kind -> backticked tokens of that kind's table row(s), from every
    markdown table whose header's first column is ``kind``.  All cells
    after the first are read — payload keys occasionally live in a
    transition/notes column (``prog`` when ``saved``)."""
    rows: Dict[str, Set[str]] = {}
    in_table = False
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "kind":
            in_table = True
            continue
        if set(cells[0]) <= {"-", ":", " "}:
            continue
        if not in_table:
            continue
        m = re.fullmatch(r"`([^`]+)`", cells[0])
        if m:
            tokens = rows.setdefault(m.group(1), set())
            for cell in cells[1:]:
                tokens |= backtick_tokens(cell)
        else:
            # a non-backticked first cell is a different table's header
            # (e.g. `| cache | count |` adjacent with no blank line) —
            # stop collecting so its rows aren't read as event kinds
            in_table = False
    return rows


class _ExtraResolver(ast.NodeVisitor):
    """Collect, per function, the constant keys flowing into each local
    name that is later ``**``-splatted: dict-literal assignments,
    ``name["key"] = ...`` subscript stores, and ``name.update({...})``
    literal merges.  Anything dynamic marks the name opaque."""

    def __init__(self) -> None:
        self.keys: Dict[str, Set[str]] = {}
        self.opaque: Set[str] = set()

    def _add_dict(self, name: str, d: ast.Dict) -> None:
        bucket = self.keys.setdefault(name, set())
        for k in d.keys:
            s = const_str(k) if k is not None else None
            if s is None:
                self.opaque.add(name)
            else:
                bucket.add(s)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Name):
                if isinstance(node.value, ast.Dict):
                    self._add_dict(t.id, node.value)
                else:
                    self.opaque.add(t.id)
            elif (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
            ):
                key = const_str(t.slice)
                if key is None:
                    self.opaque.add(t.value.id)
                else:
                    self.keys.setdefault(t.value.id, set()).add(key)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "update"
            and isinstance(f.value, ast.Name)
        ):
            name = f.value.id
            if len(node.args) == 1 and isinstance(node.args[0], ast.Dict):
                self._add_dict(name, node.args[0])
            elif node.args or node.keywords:
                self.opaque.add(name)
        self.generic_visit(node)


def _emitted(
    tree: ast.AST,
) -> Dict[str, List[Tuple[int, int, Set[str], bool]]]:
    """kind -> [(line, col, payload keys, opaque)] for every
    ``.event("kind", ...)`` call, with local ``extra`` splats resolved
    per function.  ``opaque`` marks sites whose full key set is
    unknowable statically (a non-literal splat)."""
    out: Dict[str, List[Tuple[int, int, Set[str], bool]]] = {}
    funcs: List[ast.AST] = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in funcs:
        resolver = _ExtraResolver()
        resolver.visit(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr == "event"):
                continue
            if not node.args:
                continue
            kind = const_str(node.args[0])
            if kind is None:
                continue
            keys: Set[str] = set()
            opaque = False
            for kw in node.keywords:
                if kw.arg is not None:
                    keys.add(kw.arg)
                elif isinstance(kw.value, ast.Name):
                    name = kw.value.id
                    keys |= resolver.keys.get(name, set())
                    if name in resolver.opaque or name not in resolver.keys:
                        # a splatted name the resolver never saw bound
                        # (a function parameter, an outer-scope dict) is
                        # opaque — NOT an empty key set, or GS304 would
                        # invent dead-documentation findings
                        opaque = True
                else:
                    # non-Name splats (**obj.method()) are opaque
                    opaque = True
            out.setdefault(kind, []).append(
                (node.lineno, node.col_offset, keys, opaque)
            )
    return out


@rule(codes=("GS301", "GS302", "GS303", "GS304"))
def event_schema_drift(ctx: LintContext) -> List[Finding]:
    cfg = ctx.config
    emitters = [p for p in cfg.emitter_paths if ctx.has(p)]
    if not emitters and ctx.has(cfg.engine_path):
        emitters = [cfg.engine_path]
    if not emitters or not ctx.has(cfg.events_doc_path):
        return []
    doc_text = ctx.source(cfg.events_doc_path)
    doc_rows = _doc_kind_rows(doc_text)
    doc_kinds = set(doc_rows)

    # kind -> [(path, line, col, keys, opaque)] across all emitters
    emitted: Dict[str, List[Tuple[str, int, int, Set[str], bool]]] = {}
    for path in emitters:
        for kind, sites in _emitted(ctx.tree(path)).items():
            emitted.setdefault(kind, []).extend(
                (path, line, col, keys, opaque)
                for line, col, keys, opaque in sites
            )

    out: List[Finding] = []
    for kind in sorted(emitted):
        path, line, col, _, _ = emitted[kind][0]
        if kind not in doc_kinds:
            out.append(Finding(
                "GS301", path, line, col,
                f"engine emits event kind '{kind}' that "
                f"{cfg.events_doc_path} does not document",
                f"kind:{kind}",
            ))
    for kind in sorted(doc_kinds):
        if kind not in emitted:
            out.append(Finding(
                "GS302", cfg.events_doc_path, 0, 0,
                f"{cfg.events_doc_path} documents event kind '{kind}' "
                f"that no emitter ({', '.join(emitters)}) ever emits",
                f"kind:{kind}",
            ))
    # every key any emitter produces for any kind — the schema's live
    # payload-key vocabulary.  GS304 checks documented cell tokens
    # against it, so prose tokens, outcome enums, and cache names in a
    # cell can never false-positive as "dead keys".
    live_keys: Set[str] = set()
    for sites in emitted.values():
        for _, _, _, keys, _ in sites:
            live_keys |= keys

    seen: Set[Tuple[str, str]] = set()
    for kind in sorted(emitted):
        cell = doc_rows.get(kind)
        if cell is None:
            continue  # the whole kind is already a GS301
        for path, line, col, keys, _opaque in emitted[kind]:
            for key in sorted(keys):
                if key in cell or (kind, key) in seen:
                    continue
                seen.add((kind, key))
                out.append(Finding(
                    "GS303", path, line, col,
                    f"event '{kind}' payload key '{key}' is not in the "
                    f"'{kind}' row of {cfg.events_doc_path} — document "
                    "it in the kind's payload cell",
                    f"key:{kind}.{key}",
                ))
        # GS304: dead documented keys — only when every site is fully
        # resolved (an opaque splat may legitimately carry the key)
        sites = emitted[kind]
        if any(opaque for _, _, _, _, opaque in sites):
            continue
        produced: Set[str] = set()
        for _, _, _, keys, _ in sites:
            produced |= keys
        for key in sorted((cell & live_keys) - produced):
            if not re.fullmatch(r"[a-z_][a-z0-9_]*", key):
                continue  # prose tokens (`--net`, file names) aren't keys
            if key == kind:
                continue  # rows may re-quote their own kind in prose
            out.append(Finding(
                "GS304", cfg.events_doc_path, 0, 0,
                f"{cfg.events_doc_path} documents payload key '{key}' "
                f"for kind '{kind}' that no emit site produces — dead "
                "documentation (or a missing emitter config row)",
                f"key:{kind}.{key}",
            ))
    return out
