"""GS4xx — config-hash coverage rules (ISSUE 13).

The config hash is the run's identity: ``compare`` accepts two streams
only when their hashes match, the history store keys trends by it, and
the what-if layer mirrors worlds by it.  A CLI knob that changes replay
semantics but doesn't ride the hash makes two *different* worlds look
identical — the silent-drift hazard PR 12's hardening log names.

The mapping lives in ONE table (``gpuschedule_tpu/worldspec.py``) that
``cli.py:_run_config_hash`` consumes at runtime and this rule reads
statically (AST literals — no import, so fixture trees lint the same
way).  Every argparse dest defined in ``_add_world_args`` or on the
``run`` subparser must appear in exactly one bucket:

- **GS401** flag in the CLI but in no bucket (undecided: hash it or
  allowlist it with a justification);
- **GS402** table row naming a flag the CLI no longer defines (stale);
- **GS403** ``UNHASHED`` row with an empty/missing justification.

Per-key spec coverage (ISSUE 14): the ``--faults``/``--net`` spec
STRINGS ride the hash, but the string can only express what a
``_SPEC_KEYS`` row reaches — a field added to ``FaultConfig`` /
``RecoveryModel`` / ``NetConfig`` with no spec key silently escapes the
hashed surface (its default can reshape every replay while two runs
keep one hash).  ``LintConfig.spec_tables`` names each spec table and
the config classes its rows target:

- **GS404** a config-class field no ``_SPEC_KEYS`` row reaches and the
  module's ``_UNSPECCED`` dict (field -> one-line justification) does
  not allowlist;
- **GS405** a ``_SPEC_KEYS`` row targeting an attribute that is not a
  declared field of its config class (a typo ``setattr`` would create
  silently at runtime);
- **GS406** an ``_UNSPECCED`` row that is stale (field covered by a
  spec key, or nonexistent) or carries no justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import Finding, LintContext, const_str, rule


def _dest_of(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "dest":
            return const_str(kw.value)
    # argparse derives dest from the first LONG option; fall back to
    # the first option only when no long form exists
    first = None
    for arg in call.args:
        opt = const_str(arg)
        if not opt or not opt.startswith("-"):
            continue
        if first is None:
            first = opt
        if opt.startswith("--"):
            return opt.lstrip("-").replace("-", "_")
    if first is not None:
        return first.lstrip("-").replace("-", "_")
    return None


def _add_argument_dests(
    tree: ast.AST, func_name: str, receiver: Optional[str] = None
) -> Dict[str, int]:
    """dest -> line for every ``X.add_argument(...)`` inside the named
    function; with ``receiver`` only calls on that variable count."""
    dests: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name != func_name:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if not (isinstance(f, ast.Attribute) and f.attr == "add_argument"):
                continue
            if receiver is not None and not (
                isinstance(f.value, ast.Name) and f.value.id == receiver
            ):
                continue
            dest = _dest_of(call)
            if dest:
                dests.setdefault(dest, call.lineno)
    return dests


def _table_literals(
    tree: ast.AST,
) -> Tuple[Set[str], Set[str], Dict[str, Optional[str]], Dict[str, int]]:
    """(HASHED, HASHED_WHEN_ARMED keys, UNHASHED dest->reason,
    name->line) from the worldspec module's top-level literals."""
    hashed: Set[str] = set()
    armed: Set[str] = set()
    unhashed: Dict[str, Optional[str]] = {}
    lines: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id == "HASHED" and isinstance(node.value, (ast.Tuple, ast.List)):
                for el in node.value.elts:
                    s = const_str(el)
                    if s:
                        hashed.add(s)
                        lines[s] = el.lineno
            elif t.id == "HASHED_WHEN_ARMED" and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    s = const_str(k) if k is not None else None
                    if s:
                        armed.add(s)
                        lines[s] = k.lineno
            elif t.id == "UNHASHED" and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    s = const_str(k) if k is not None else None
                    if s:
                        unhashed[s] = const_str(v)
                        lines[s] = k.lineno
    return hashed, armed, unhashed, lines


@rule(codes=("GS401", "GS402", "GS403"))
def config_hash_coverage(ctx: LintContext) -> List[Finding]:
    cfg = ctx.config
    if not ctx.has(cfg.cli_path) or not ctx.has(cfg.worldspec_path):
        return []
    cli_tree = ctx.tree(cfg.cli_path)
    dests: Dict[str, int] = {}
    dests.update(_add_argument_dests(cli_tree, "_add_world_args"))
    # the flags of every subparser that builds a hashed world (run,
    # whatif), defined inside main() on their parser variables
    for receiver in cfg.world_parser_receivers:
        for d, ln in _add_argument_dests(
            cli_tree, "main", receiver=receiver
        ).items():
            dests.setdefault(d, ln)

    hashed, armed, unhashed, lines = _table_literals(
        ctx.tree(cfg.worldspec_path)
    )
    covered = hashed | armed | set(unhashed)

    out: List[Finding] = []
    for dest in sorted(dests):
        if dest not in covered:
            out.append(Finding(
                "GS401", cfg.cli_path, dests[dest], 0,
                f"CLI flag '{dest}' (world/run surface) is neither hashed "
                f"nor allowlisted in {cfg.worldspec_path} — decide: does "
                "it change replay semantics?",
                dest,
            ))
    for name in sorted(covered):
        if name not in dests:
            out.append(Finding(
                "GS402", cfg.worldspec_path, lines.get(name, 0), 0,
                f"worldspec table row '{name}' matches no _add_world_args "
                "/ run flag — remove the stale row",
                name,
            ))
    for name in sorted(unhashed):
        reason = unhashed[name]
        if not reason or not reason.strip():
            out.append(Finding(
                "GS403", cfg.worldspec_path, lines.get(name, 0), 0,
                f"UNHASHED row '{name}' has no justification — every "
                "deliberately-unhashed knob documents why",
                name,
            ))
    return out


# --------------------------------------------------------------------- #
# per-key spec coverage (ISSUE 14)


def _spec_rows(
    tree: ast.AST, table_name: str
) -> Optional[Dict[str, Tuple[str, str, int]]]:
    """spec key -> (target label, target attr, line) from the module's
    ``_SPEC_KEYS`` literal.  Plain-string values use label ""."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == table_name
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        rows: Dict[str, Tuple[str, str, int]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            key = const_str(k) if k is not None else None
            if key is None:
                continue
            attr = const_str(v)
            if attr is not None:
                rows[key] = ("", attr, k.lineno)
            elif isinstance(v, (ast.Tuple, ast.List)) and len(v.elts) == 2:
                label, attr = const_str(v.elts[0]), const_str(v.elts[1])
                if label is not None and attr is not None:
                    rows[key] = (label, attr, k.lineno)
        return rows
    return None


def _dataclass_fields(tree: ast.AST, class_name: str) -> Optional[Dict[str, int]]:
    """field -> line for a config dataclass's declared fields (class-body
    ``name: ann [= default]`` statements; methods/underscored ignored)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            fields: Dict[str, int] = {}
            for sub in node.body:
                if isinstance(sub, ast.AnnAssign) and isinstance(
                    sub.target, ast.Name
                ):
                    if not sub.target.id.startswith("_"):
                        fields[sub.target.id] = sub.lineno
            return fields
    return None


def _unspecced(tree: ast.AST) -> Tuple[Dict[str, Optional[str]], Dict[str, int]]:
    """The module's ``_UNSPECCED`` allowlist (field -> reason, + lines)."""
    out: Dict[str, Optional[str]] = {}
    lines: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Name) and t.id == "_UNSPECCED"
                and isinstance(node.value, ast.Dict)
            ):
                for k, v in zip(node.value.keys, node.value.values):
                    s = const_str(k) if k is not None else None
                    if s is not None:
                        out[s] = const_str(v)
                        lines[s] = k.lineno
    return out, lines


@rule(codes=("GS404", "GS405", "GS406"))
def spec_key_hash_coverage(ctx: LintContext) -> List[Finding]:
    out: List[Finding] = []
    for spec_path, table_name, targets in ctx.config.spec_tables:
        if not ctx.has(spec_path):
            continue
        spec_tree = ctx.tree(spec_path)
        rows = _spec_rows(spec_tree, table_name)
        if rows is None:
            continue
        allow, allow_lines = _unspecced(spec_tree)

        # label -> (class path, class name, its fields) — "" class paths
        # mark exempt dynamic buckets (the domain-weight dict)
        classes: Dict[str, Optional[Tuple[str, str, Dict[str, int]]]] = {}
        for label, cls_path, cls_name in targets:
            if not cls_path:
                classes[label] = None
                continue
            if not ctx.has(cls_path):
                continue
            fields = _dataclass_fields(ctx.tree(cls_path), cls_name)
            if fields is not None:
                classes[label] = (cls_path, cls_name, fields)

        covered: Dict[str, Set[str]] = {}  # label -> reached attrs
        for key in sorted(rows):
            label, attr, line = rows[key]
            if label not in classes:
                continue  # unknown bucket: a fixture subset, skip
            target = classes[label]
            if target is None:
                continue  # exempt dynamic bucket
            cls_path, cls_name, fields = target
            covered.setdefault(label, set()).add(attr)
            if attr not in fields:
                out.append(Finding(
                    "GS405", spec_path, line, 0,
                    f"{table_name} row '{key}' targets {cls_name}.{attr} "
                    "which is not a declared field — a runtime setattr "
                    "would create it silently (stale row or typo)",
                    f"{key}->{cls_name}.{attr}",
                ))

        for label in sorted(classes):
            target = classes[label]
            if target is None:
                continue
            cls_path, cls_name, fields = target
            reached = covered.get(label, set())
            for attr in sorted(fields):
                if attr in reached:
                    continue
                if attr in allow:
                    continue
                out.append(Finding(
                    "GS404", cls_path, fields[attr], 0,
                    f"{cls_name}.{attr} is reachable by no {table_name} "
                    f"key in {spec_path} and not allowlisted in "
                    "_UNSPECCED — only the spec STRING rides the config "
                    "hash, so this field escapes the hashed surface",
                    f"{cls_name}.{attr}",
                ))

        # which labels declare each field name — same-named fields on
        # two audited classes stay distinguishable: an allowlist row is
        # stale only when EVERY declaring class has the field reached
        declaring: Dict[str, List[str]] = {}
        for label, target in classes.items():
            if target is not None:
                for attr in target[2]:
                    declaring.setdefault(attr, []).append(label)
        for name in sorted(allow):
            reason = allow[name]
            line = allow_lines.get(name, 0)
            if not reason or not reason.strip():
                out.append(Finding(
                    "GS406", spec_path, line, 0,
                    f"_UNSPECCED row '{name}' has no justification — "
                    "every field deliberately outside the spec surface "
                    "documents why",
                    f"{name}:unjustified",
                ))
            labels = declaring.get(name)
            if labels is None:
                out.append(Finding(
                    "GS406", spec_path, line, 0,
                    f"_UNSPECCED row '{name}' names no declared field of "
                    "the audited config classes — remove the stale row",
                    f"{name}:stale",
                ))
            elif all(name in covered.get(lb, set()) for lb in labels):
                out.append(Finding(
                    "GS406", spec_path, line, 0,
                    f"_UNSPECCED row '{name}' is stale: a {table_name} "
                    "key now reaches that field on every declaring "
                    "class — remove the allowlist row",
                    f"{name}:stale",
                ))
    return out
