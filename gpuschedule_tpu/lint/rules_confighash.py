"""GS4xx — config-hash coverage rules (ISSUE 13).

The config hash is the run's identity: ``compare`` accepts two streams
only when their hashes match, the history store keys trends by it, and
the what-if layer mirrors worlds by it.  A CLI knob that changes replay
semantics but doesn't ride the hash makes two *different* worlds look
identical — the silent-drift hazard PR 12's hardening log names.

The mapping lives in ONE table (``gpuschedule_tpu/worldspec.py``) that
``cli.py:_run_config_hash`` consumes at runtime and this rule reads
statically (AST literals — no import, so fixture trees lint the same
way).  Every argparse dest defined in ``_add_world_args`` or on the
``run`` subparser must appear in exactly one bucket:

- **GS401** flag in the CLI but in no bucket (undecided: hash it or
  allowlist it with a justification);
- **GS402** table row naming a flag the CLI no longer defines (stale);
- **GS403** ``UNHASHED`` row with an empty/missing justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from gpuschedule_tpu.lint.core import Finding, LintContext, const_str, rule


def _dest_of(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "dest":
            return const_str(kw.value)
    # argparse derives dest from the first LONG option; fall back to
    # the first option only when no long form exists
    first = None
    for arg in call.args:
        opt = const_str(arg)
        if not opt or not opt.startswith("-"):
            continue
        if first is None:
            first = opt
        if opt.startswith("--"):
            return opt.lstrip("-").replace("-", "_")
    if first is not None:
        return first.lstrip("-").replace("-", "_")
    return None


def _add_argument_dests(
    tree: ast.AST, func_name: str, receiver: Optional[str] = None
) -> Dict[str, int]:
    """dest -> line for every ``X.add_argument(...)`` inside the named
    function; with ``receiver`` only calls on that variable count."""
    dests: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name != func_name:
            continue
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if not (isinstance(f, ast.Attribute) and f.attr == "add_argument"):
                continue
            if receiver is not None and not (
                isinstance(f.value, ast.Name) and f.value.id == receiver
            ):
                continue
            dest = _dest_of(call)
            if dest:
                dests.setdefault(dest, call.lineno)
    return dests


def _table_literals(
    tree: ast.AST,
) -> Tuple[Set[str], Set[str], Dict[str, Optional[str]], Dict[str, int]]:
    """(HASHED, HASHED_WHEN_ARMED keys, UNHASHED dest->reason,
    name->line) from the worldspec module's top-level literals."""
    hashed: Set[str] = set()
    armed: Set[str] = set()
    unhashed: Dict[str, Optional[str]] = {}
    lines: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id == "HASHED" and isinstance(node.value, (ast.Tuple, ast.List)):
                for el in node.value.elts:
                    s = const_str(el)
                    if s:
                        hashed.add(s)
                        lines[s] = el.lineno
            elif t.id == "HASHED_WHEN_ARMED" and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    s = const_str(k) if k is not None else None
                    if s:
                        armed.add(s)
                        lines[s] = k.lineno
            elif t.id == "UNHASHED" and isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    s = const_str(k) if k is not None else None
                    if s:
                        unhashed[s] = const_str(v)
                        lines[s] = k.lineno
    return hashed, armed, unhashed, lines


@rule
def config_hash_coverage(ctx: LintContext) -> List[Finding]:
    cfg = ctx.config
    if not ctx.has(cfg.cli_path) or not ctx.has(cfg.worldspec_path):
        return []
    cli_tree = ctx.tree(cfg.cli_path)
    dests: Dict[str, int] = {}
    dests.update(_add_argument_dests(cli_tree, "_add_world_args"))
    # the flags of every subparser that builds a hashed world (run,
    # whatif), defined inside main() on their parser variables
    for receiver in cfg.world_parser_receivers:
        for d, ln in _add_argument_dests(
            cli_tree, "main", receiver=receiver
        ).items():
            dests.setdefault(d, ln)

    hashed, armed, unhashed, lines = _table_literals(
        ctx.tree(cfg.worldspec_path)
    )
    covered = hashed | armed | set(unhashed)

    out: List[Finding] = []
    for dest in sorted(dests):
        if dest not in covered:
            out.append(Finding(
                "GS401", cfg.cli_path, dests[dest], 0,
                f"CLI flag '{dest}' (world/run surface) is neither hashed "
                f"nor allowlisted in {cfg.worldspec_path} — decide: does "
                "it change replay semantics?",
                dest,
            ))
    for name in sorted(covered):
        if name not in dests:
            out.append(Finding(
                "GS402", cfg.worldspec_path, lines.get(name, 0), 0,
                f"worldspec table row '{name}' matches no _add_world_args "
                "/ run flag — remove the stale row",
                name,
            ))
    for name in sorted(unhashed):
        reason = unhashed[name]
        if not reason or not reason.strip():
            out.append(Finding(
                "GS403", cfg.worldspec_path, lines.get(name, 0), 0,
                f"UNHASHED row '{name}' has no justification — every "
                "deliberately-unhashed knob documents why",
                name,
            ))
    return out
