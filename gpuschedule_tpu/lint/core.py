"""Contract-linter core (ISSUE 13): findings, rule engine, baseline.

The linter is a repo-aware static-analysis pass: every rule reads the
repository's own Python ASTs (and docs) and enforces one of the
invariants PRs 1-12 established by hand — determinism, seed-stream
namespacing, event-schema/doc agreement, config-hash coverage, cache
discipline, fork safety.  Zero dependencies beyond the stdlib ``ast``
module; output is deterministic (sorted findings, no timestamps, no
absolute paths) so repeated runs produce byte-identical JSON and the
report can ride the PR-10 history store.

Suppression surfaces (both audited — see docs/static-analysis.md):

- **inline pragma**: ``# lint: allow[GS101] reason`` on the flagged
  line or the line directly above suppresses matching findings; a
  pragma without a reason is itself a finding (GS002).
- **baseline file** (``tools/lint_baseline.json``): entries match on
  ``(code, path, detail)`` — the stable fingerprint, deliberately not
  the line number, so baselines survive unrelated edits.  A baseline
  entry that matches nothing is a finding (GS001: stale), which keeps
  the file honest as violations get fixed.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PACKAGE = "gpuschedule_tpu"

# pragma grammar: "# lint: allow[GS101]" or "# lint: allow[GS101,GS601]",
# reason text required after the bracket
_PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\[([A-Z0-9, ]+)\]\s*(.*)")


@dataclass(frozen=True)
class Finding:
    """One contract violation.  ``detail`` is the stable fingerprint
    token baseline entries match on (a dotted name, a stream template,
    an attribute name — never a line number)."""

    code: str
    path: str            # repo-relative posix path
    line: int
    col: int
    message: str
    detail: str

    def key(self) -> Tuple[str, str, int, int, str]:
        return (self.path, self.line, self.col, self.code, self.detail)

    def to_json(self) -> dict:
        return {
            "code": self.code, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message, "detail": self.detail,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col} {self.code} {self.message}"


@dataclass
class LintConfig:
    """Where each repo-aware rule looks.  Defaults describe this
    repository; fixture tests point ``run_lint`` at miniature trees with
    the same layout (tests/lint_fixtures/)."""

    package: str = PACKAGE
    # directories excluded from the package walk anywhere in the path:
    # fixture trees (miniature checkouts used by the linter's own tests)
    # must never be linted as product code when --root points at a tree
    # that happens to nest them (ISSUE 14 satellite)
    exclude_dirs: Tuple[str, ...] = ("tests", "lint_fixtures")
    # rule GS1xx: modules whose replay semantics must be deterministic
    determinism_dirs: Tuple[str, ...] = ("sim", "net", "faults", "cluster")
    # ...plus individual files outside those dirs whose OUTPUT must be a
    # pure function of the stream they read: the watchtower's alert
    # sequence is a determinism contract (ISSUE 15), so its wall-clock
    # reads (follow-mode polling) carry reasoned pragmas like the
    # engine's own measurement sites
    # ... and the cross-process fleet layer (ISSUE 16): its federated
    # document must be a pure function of the worker payloads, so its
    # wall anchors / process-local harness globals carry reasoned
    # pragmas like the engine's own measurement sites
    # ... and the serving daemon (ISSUE 18): everything it serves must
    # be a pure function of (world, mirror instant, stream, queries) —
    # wall clock lives only at the HTTP edge (uptime, drain deadlines,
    # SSE keepalives), each read behind a reasoned pragma
    determinism_files: Tuple[str, ...] = (
        f"{PACKAGE}/obs/watch.py",
        f"{PACKAGE}/obs/fleet.py",
        f"{PACKAGE}/obs/server.py",
    )
    # rule GS3xx: the event emitters and their schema document.  Every
    # path in emitter_paths is scanned for ``.event(...)`` calls — the
    # engine is joined by the what-if / snapshot layers and the
    # watchtower's alert side stream (ISSUE 15) so a second emitter
    # growing an event site is linted from day one (ISSUE 14)
    engine_path: str = f"{PACKAGE}/sim/engine.py"
    emitter_paths: Tuple[str, ...] = (
        f"{PACKAGE}/sim/engine.py",
        f"{PACKAGE}/sim/whatif.py",
        f"{PACKAGE}/sim/snapshot.py",
        f"{PACKAGE}/obs/watch.py",
    )
    events_doc_path: str = "docs/events.md"
    # rule GS4xx: the argparse definitions and the shared hash table;
    # every subparser variable that builds a hashed world is audited
    cli_path: str = f"{PACKAGE}/cli.py"
    worldspec_path: str = f"{PACKAGE}/worldspec.py"
    world_parser_receivers: Tuple[str, ...] = ("run", "wi", "sv")
    # rule GS41x: per-key spec-table audit (ISSUE 14) — each row is
    # (spec module, table name, ((target label, config module, config
    # class), ...)).  A table whose values are plain attribute strings
    # uses the single row with label "" ; a label mapping to ("", "")
    # is exempt (it targets a dynamic bucket, not a dataclass field).
    spec_tables: Tuple[
        Tuple[str, str, Tuple[Tuple[str, str, str], ...]], ...
    ] = (
        (f"{PACKAGE}/faults/schedule.py", "_SPEC_KEYS", (
            ("config", f"{PACKAGE}/faults/schedule.py", "FaultConfig"),
            ("recovery", f"{PACKAGE}/faults/recovery.py", "RecoveryModel"),
            ("weight", "", ""),
        )),
        (f"{PACKAGE}/net/model.py", "_SPEC_KEYS", (
            ("", f"{PACKAGE}/net/model.py", "NetConfig"),
        )),
    )
    # rule GS2xx: the declared seed-stream registry (None = the repo's
    # own registry from gpuschedule_tpu/lint/seed_registry.py)
    seed_streams: Optional[Dict[str, str]] = None
    shared_seed_streams: Tuple[str, ...] = ()
    # rule GS7xx: the analyzer's transition table and the engine's
    # job-state vocabulary (ISSUE 14).  state_aliases maps engine
    # JobState values onto the analyzer's state names; job_set_attrs
    # gives the states of jobs iterated off the engine's membership
    # containers (``self.running`` / ``self.pending``).
    analyzer_path: str = f"{PACKAGE}/obs/analyze.py"
    legal_from_name: str = "_LEGAL_FROM"
    job_state_path: str = f"{PACKAGE}/sim/job.py"
    job_state_class: str = "JobState"
    state_aliases: Tuple[Tuple[str, str], ...] = (("pending", "queued"),)
    job_set_attrs: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
        ("running", ("running",)),
        ("pending", ("queued", "suspended")),
    )


class LintContext:
    """Parsed-once view of the tree: source text, lines, and ASTs for
    every package file, plus the docs the schema rules read."""

    def __init__(self, root: Path, config: LintConfig):
        self.root = Path(root)
        self.config = config
        self._sources: Dict[str, str] = {}
        self._lines: Dict[str, List[str]] = {}
        self._trees: Dict[str, ast.AST] = {}
        self._comments: Dict[str, Dict[int, str]] = {}
        self._symbols = None
        pkg = self.root / config.package
        # exclusion applies to parts BELOW the package dir only: a
        # fixture tree may itself live under a tests/ prefix, but a
        # tests/ (or nested fixture) subtree inside the scanned package
        # must never be linted as product code (ISSUE 14 satellite)
        skip = set(config.exclude_dirs) | {"__pycache__"}
        self.py_files: List[str] = sorted(
            p.relative_to(self.root).as_posix()
            for p in pkg.rglob("*.py")
            if not skip.intersection(p.relative_to(pkg).parts)
        )

    def has(self, rel: str) -> bool:
        return (self.root / rel).is_file()

    def source(self, rel: str) -> str:
        if rel not in self._sources:
            self._sources[rel] = (self.root / rel).read_text()
        return self._sources[rel]

    def lines(self, rel: str) -> List[str]:
        if rel not in self._lines:
            self._lines[rel] = self.source(rel).splitlines()
        return self._lines[rel]

    def tree(self, rel: str) -> ast.AST:
        if rel not in self._trees:
            self._trees[rel] = ast.parse(self.source(rel), filename=rel)
        return self._trees[rel]

    def symbols(self):
        """The package-wide symbol table (lint/symbols.py), built once
        per context and shared by every whole-program rule."""
        if self._symbols is None:
            from gpuschedule_tpu.lint.symbols import SymbolTable

            self._symbols = SymbolTable(self)
        return self._symbols

    def comments(self, rel: str) -> Dict[int, str]:
        """line -> comment text, via the tokenizer — so pragma matching
        never fires on pragma-shaped text inside a string/docstring."""
        if rel not in self._comments:
            out: Dict[int, str] = {}
            try:
                toks = tokenize.generate_tokens(
                    io.StringIO(self.source(rel)).readline
                )
                for tok in toks:
                    if tok.type == tokenize.COMMENT:
                        out[tok.start[0]] = tok.string
            except tokenize.TokenError:
                pass
            self._comments[rel] = out
        return self._comments[rel]


Rule = Callable[[LintContext], List[Finding]]
_RULES: List[Rule] = []  # lint: allow[GS601] populated once at rule-module import; every process re-imports identically
_RULE_CODES: Dict[str, Tuple[str, ...]] = {}  # lint: allow[GS601] same import-time registry


def rule(fn: Optional[Rule] = None, *, codes: Tuple[str, ...] = ()):
    """Register a rule: a callable taking the context and returning
    findings.  Registration order is irrelevant — findings are sorted.
    ``codes`` declares the GS codes the rule can produce; the union
    across rules is the ``rules`` coverage count the history store
    trends (ISSUE 14 satellite)."""
    def register(f: Rule) -> Rule:
        _RULES.append(f)
        _RULE_CODES[f.__name__] = tuple(codes)
        return f

    if fn is not None:
        return register(fn)
    return register


def registered_codes() -> Tuple[str, ...]:
    """Every GS code the registered rules declare, sorted — the linter's
    enforced-contract surface (plus the engine's own GS001/GS002)."""
    out = {"GS001", "GS002"}
    for codes in _RULE_CODES.values():
        out.update(codes)
    return tuple(sorted(out))


# ---------------------------------------------------------------------- #
# shared AST helpers (used by several rules)

def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Name -> dotted-module/attribute map from a module's imports:
    ``import time as t`` -> {"t": "time"}; ``from time import
    perf_counter as pc`` -> {"pc": "time.perf_counter"}."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Resolve a Name/Attribute chain to its dotted import-rooted form
    (``t.perf_counter`` with ``import time as t`` -> "time.perf_counter");
    None when the chain doesn't root at an imported name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    base = aliases.get(node.id)
    if base is None:
        return None
    parts.append(base)
    return ".".join(reversed(parts))


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def backtick_tokens(text: str) -> set:
    """Every `backtick`-quoted token in a markdown document.  Code
    fences (```) are stripped first — their triple backticks would
    otherwise desynchronize the pairing — and tokens never span lines.
    For prose-shaped tokens like ``warned: true`` the leading
    identifier is extracted too, so a documented key matches however
    the prose quotes it."""
    tokens = set(re.findall(r"`([^`\n]+)`", text.replace("```", "")))
    for t in list(tokens):
        m = re.match(r"[A-Za-z_][A-Za-z0-9_]*", t)
        if m:
            tokens.add(m.group(0))
    return tokens


# ---------------------------------------------------------------------- #
# baseline + pragma suppression

def load_baseline(path: Path) -> List[dict]:
    doc = json.loads(path.read_text())
    entries = doc.get("entries") if isinstance(doc, dict) else doc
    if not isinstance(entries, list):
        raise ValueError(
            f"baseline {path}: expected a JSON list or an object with an "
            "'entries' list"
        )
    for e in entries:
        if not isinstance(e, dict):
            raise ValueError(f"baseline entry {e!r}: must be an object")
        for k in ("code", "path", "detail", "justification"):
            if not isinstance(e.get(k), str) or not e[k].strip():
                raise ValueError(
                    f"baseline entry {e!r}: '{k}' must be a non-empty string"
                )
    return entries


def _pragma_allows(ctx: LintContext, f: Finding) -> Optional[bool]:
    """True: suppressed by a reasoned pragma.  False: pragma present but
    reasonless (caller turns that into GS002).  None: no pragma."""
    if f.line <= 0 or not ctx.has(f.path):
        # aggregate findings (stale registry/baseline rows, doc-side
        # drift) anchor to a file:0 label, not a source line
        return None
    if not f.path.endswith(".py"):
        return None
    comments = ctx.comments(f.path)
    for ln in (f.line, f.line - 1):
        comment = comments.get(ln)
        if comment is None:
            continue
        m = _PRAGMA_RE.search(comment)
        if m and f.code in {c.strip() for c in m.group(1).split(",")}:
            return bool(m.group(2).strip())
    return None


@dataclass
class LintReport:
    findings: List[Finding]            # unsuppressed — these gate
    baselined: int = 0
    allowed: int = 0                   # pragma-suppressed
    files_scanned: int = 0
    rules_run: int = 0
    rules: int = 0                     # distinct enforced GS codes
    codes: Dict[str, int] = field(default_factory=dict)
    # wall-clock seconds per rule function, plus "total" — measurement,
    # NOT part of the deterministic report (render_json excludes it);
    # the CI gate (tools/contract_lint.py) prints and budgets it
    timings: Dict[str, float] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def summary_metrics(self) -> Dict[str, int]:
        """Flat numeric summary — the shape the PR-10 history store
        ingests (``lint --history``).  ``rules`` counts the distinct GS
        codes the registered rules enforce, so ``history trend`` shows
        contract coverage growing across versions (ISSUE 14)."""
        out = {
            "findings": len(self.findings),
            "baselined": self.baselined,
            "allowed": self.allowed,
            "files_scanned": self.files_scanned,
            "rules_run": self.rules_run,
            "rules": self.rules,
            "ok": int(self.ok),
        }
        for code, n in sorted(self.codes.items()):
            out[f"findings_{code}"] = n
        return out

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [f.to_json() for f in self.findings],
            "baselined": self.baselined,
            "allowed": self.allowed,
            "files_scanned": self.files_scanned,
            "rules_run": self.rules_run,
            "rules": self.rules,
            "codes": dict(sorted(self.codes.items())),
        }

    def render_json(self) -> str:
        """Deterministic bytes: same tree + baseline -> same output."""
        return json.dumps(self.to_json(), indent=2, sort_keys=True) + "\n"


def run_lint(
    root,
    *,
    config: Optional[LintConfig] = None,
    baseline: Optional[Sequence[dict]] = None,
) -> LintReport:
    """Run every registered rule over the tree at ``root`` and fold the
    raw findings through pragma + baseline suppression."""
    import time

    # rule modules self-register on import
    from gpuschedule_tpu.lint import (  # noqa: F401
        rules_cache,
        rules_confighash,
        rules_determinism,
        rules_forksafety,
        rules_schema,
        rules_seeds,
        rules_statemachine,
    )

    ctx = LintContext(Path(root), config or LintConfig())
    raw: List[Finding] = []
    timings: Dict[str, float] = {}
    t_all = time.perf_counter()
    for fn in _RULES:
        t0 = time.perf_counter()
        raw.extend(fn(ctx))
        timings[fn.__name__] = (
            timings.get(fn.__name__, 0.0) + time.perf_counter() - t0
        )
    timings["total"] = time.perf_counter() - t_all

    entries = list(baseline or ())
    matched = [False] * len(entries)
    kept: List[Finding] = []
    baselined = allowed = 0
    for f in raw:
        verdict = _pragma_allows(ctx, f)
        if verdict is True:
            allowed += 1
            continue
        if verdict is False:
            f = Finding(
                "GS002", f.path, f.line, f.col,
                f"pragma suppressing {f.code} has no justification text",
                f.detail,
            )
        hit = False
        for i, e in enumerate(entries):
            if (e["code"], e["path"], e["detail"]) == (f.code, f.path, f.detail):
                matched[i] = True
                hit = True
        if hit:
            baselined += 1
        else:
            kept.append(f)
    for e, m in zip(entries, matched):
        if not m:
            kept.append(Finding(
                "GS001", e["path"], 0, 0,
                f"stale baseline entry: no {e['code']} finding with detail "
                f"'{e['detail']}' — remove it",
                e["detail"],
            ))

    kept.sort(key=Finding.key)
    codes: Dict[str, int] = {}
    for f in kept:
        codes[f.code] = codes.get(f.code, 0) + 1
    return LintReport(
        findings=kept, baselined=baselined, allowed=allowed,
        files_scanned=len(ctx.py_files), rules_run=len(_RULES),
        rules=len(registered_codes()), codes=codes, timings=timings,
    )
