"""Result analysis: JCT CDFs, policy/topology comparison reports.

The reference ships Jupyter notebooks that run experiment grids and plot
JCT CDFs / makespan bars (SURVEY.md §2 "Notebooks", §3.4).  This module is
the library form of those notebooks — pure functions over SimResults that
the CLI's ``compare`` / ``report`` commands and any notebook can call;
outputs are plain dict/CSV so pandas/matplotlib consumption is one line.
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from gpuschedule_tpu.sim.metrics import SimResult


def jct_cdf(result: SimResult, *, points: int = 100) -> List[Tuple[float, float]]:
    """(jct_seconds, cumulative_fraction) pairs — the notebook headline plot."""
    jcts = sorted(j.jct() for j in result.jobs if j.jct() is not None and j.state.value != "rejected")
    if not jcts:
        return []
    n = len(jcts)
    step = max(1, n // points)
    out = [(jcts[i], (i + 1) / n) for i in range(0, n, step)]
    # ensure the curve reaches 1.0 even when the max JCT value is tied with
    # the last sampled point (comparing values instead of fractions here
    # used to leave the CDF topping out below 1)
    if out[-1][1] != 1.0:
        if out[-1][0] == jcts[-1]:
            out[-1] = (jcts[-1], 1.0)
        else:
            out.append((jcts[-1], 1.0))
    return out


def summarize(results: Dict[str, SimResult]) -> Dict[str, dict]:
    """name -> headline metrics, for grid experiments."""
    return {name: res.summary() for name, res in results.items()}


def write_report(
    results: Dict[str, SimResult],
    out_dir: str | Path,
    *,
    prefix: str = "",
) -> None:
    """Persist a comparison: summary JSON + per-config JCT CDF CSVs +
    a markdown table (the notebook's bar-chart data in text form)."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    summary = summarize(results)
    with open(out / f"{prefix}summary.json", "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
    for name, res in results.items():
        with open(out / f"{prefix}cdf_{name}.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["jct_seconds", "cum_fraction"])
            w.writerows(jct_cdf(res))
    lines = [
        "| config | avg JCT (s) | makespan (s) | p95 queue (s) | util | finished | rejected |",
        "|---|---|---|---|---|---|---|",
    ]
    for name in sorted(results):
        s = summary[name]
        lines.append(
            f"| {name} | {s['avg_jct']:.1f} | {s['makespan']:.1f} | "
            f"{s['p95_queueing_delay']:.1f} | {s['mean_utilization']:.3f} | "
            f"{int(s['num_finished'])} | {int(s.get('num_rejected', 0))} |"
        )
    (out / f"{prefix}report.md").write_text("\n".join(lines) + "\n")
