"""Result analysis: JCT CDFs, policy/topology comparison reports.

The reference ships Jupyter notebooks that run experiment grids and plot
JCT CDFs / makespan bars (SURVEY.md §2 "Notebooks", §3.4).  This module is
the library form of those notebooks — pure functions over SimResults that
the CLI's ``compare`` / ``report`` commands and any notebook can call;
outputs are plain dict/CSV so pandas/matplotlib consumption is one line.
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from gpuschedule_tpu.sim.metrics import SimResult


def jct_cdf(result: SimResult, *, points: int = 100) -> List[Tuple[float, float]]:
    """(jct_seconds, cumulative_fraction) pairs — the notebook headline plot."""
    jcts = sorted(j.jct() for j in result.jobs if j.jct() is not None and j.state.value != "rejected")
    if not jcts:
        return []
    n = len(jcts)
    step = max(1, n // points)
    out = [(jcts[i], (i + 1) / n) for i in range(0, n, step)]
    # ensure the curve reaches 1.0 even when the max JCT value is tied with
    # the last sampled point (comparing values instead of fractions here
    # used to leave the CDF topping out below 1)
    if out[-1][1] != 1.0:
        if out[-1][0] == jcts[-1]:
            out[-1] = (jcts[-1], 1.0)
        else:
            out.append((jcts[-1], 1.0))
    return out


def summarize(results: Dict[str, SimResult]) -> Dict[str, dict]:
    """name -> headline metrics, for grid experiments."""
    return {name: res.summary() for name, res in results.items()}


ACCEPTANCE_THRESHOLD_PCT = 5.0  # fixed by the BASELINE.json:5 contract


def scale_offered_load(jobs, factor: float):
    """Rescale a trace's offered load in place by stretching arrivals.

    ``factor`` < 1 lowers the load (inter-arrival gaps divide by it); job
    sizes and durations are untouched, so only queueing pressure changes.
    Returns the same list for chaining.
    """
    if factor <= 0:
        raise ValueError(f"load factor must be positive, got {factor}")
    for j in jobs:
        j.submit_time = j.submit_time / factor
    return jobs


def acceptance_load_sweep(
    make_jobs,
    baseline_factory,
    candidate_factory,
    policy_factory,
    *,
    loads: Sequence[float] = (0.70, 0.80, 0.90, 0.95),
    base_load: float = 0.95,
    base_results=None,
) -> Dict[str, dict]:
    """The acceptance band as a function of offered load.

    The round-3 verdict (weak #7) asked for the curve behind the plain-
    FIFO knowing-pin: at the published arrival rate the 10k replay runs
    ~95% offered load, where HOL queueing explodes any capacity the pow2
    round-up forfeits; sweeping the load shows where the policy re-enters
    the band — and catches a future allocator regression that a single
    already-huge delta would hide.  Each entry replays baseline and
    candidate clusters on the same load-rescaled trace.
    """
    from gpuschedule_tpu.sim.engine import Simulator

    out: Dict[str, dict] = {}
    for load in loads:
        if base_results is not None and abs(load - base_load) < 1e-12:
            # the caller already replayed the unscaled trace: reuse
            out[f"{load:.2f}"] = acceptance_band(*base_results)
            continue
        factor = load / base_load
        base = Simulator(
            baseline_factory(), policy_factory(),
            scale_offered_load(make_jobs(), factor),
        ).run()
        cand = Simulator(
            candidate_factory(), policy_factory(),
            scale_offered_load(make_jobs(), factor),
        ).run()
        out[f"{load:.2f}"] = acceptance_band(base, cand)
    return out


def acceptance_band(baseline: SimResult, candidate: SimResult) -> dict:
    """The BASELINE.json:5 contract, computed: is the TPU replay's avg-JCT
    and makespan within 5% of the GPU-backed baseline?

    Deltas are signed percentages relative to the baseline (negative =
    candidate better).  "Within" is one-sided: a candidate that *beats* the
    baseline by more than the threshold still satisfies the contract — the
    band bounds regression, not improvement.  A delta is ``None`` (and the
    verdict False) when the baseline metric is zero with a nonzero
    candidate — undefined rather than infinite, so the dict stays strict
    JSON.
    """
    b, c = baseline.summary(), candidate.summary()

    def delta(key: str):
        if b[key] == 0:
            return 0.0 if c[key] == 0 else None
        return 100.0 * (c[key] - b[key]) / b[key]

    jct = delta("avg_jct")
    mk = delta("makespan")
    t = ACCEPTANCE_THRESHOLD_PCT
    return {
        "jct_delta_pct": jct,
        "makespan_delta_pct": mk,
        "threshold_pct": t,
        "within_5pct": jct is not None and mk is not None and jct <= t and mk <= t,
    }


def write_report(
    results: Dict[str, SimResult],
    out_dir: str | Path,
    *,
    prefix: str = "",
    extra: Optional[dict] = None,
) -> None:
    """Persist a comparison: summary JSON + per-config JCT CDF CSVs +
    a markdown table (the notebook's bar-chart data in text form).

    ``extra`` entries (e.g. the :func:`acceptance_band` verdict) are merged
    into the summary JSON under their own keys and appended to the report.
    """
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    summary = summarize(results)
    payload = dict(summary)
    if extra:
        overlap = set(extra) & set(payload)
        if overlap:
            raise ValueError(f"extra keys collide with config names: {sorted(overlap)}")
        payload.update(extra)
    with open(out / f"{prefix}summary.json", "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
    for name, res in results.items():
        with open(out / f"{prefix}cdf_{name}.csv", "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["jct_seconds", "cum_fraction"])
            w.writerows(jct_cdf(res))
    lines = [
        "| config | avg JCT (s) | makespan (s) | p95 queue (s) | "
        "p95 slowdown | util | finished | rejected |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for name in sorted(results):
        s = summary[name]
        lines.append(
            f"| {name} | {s['avg_jct']:.1f} | {s['makespan']:.1f} | "
            f"{s['p95_queueing_delay']:.1f} | "
            f"{s['p95_slowdown']:.2f} | "
            f"{s['mean_utilization']:.3f} | "
            f"{int(s['num_finished'])} | {int(s.get('num_rejected', 0))} |"
        )
    if extra and "acceptance" in extra:
        a = extra["acceptance"]

        def fmt(d):
            return "undefined (zero baseline)" if d is None else f"{d:+.2f}%"

        lines += [
            "",
            f"**Acceptance (BASELINE.json:5, ±{a['threshold_pct']:g}% band):** "
            f"avg-JCT delta {fmt(a['jct_delta_pct'])}, "
            f"makespan delta {fmt(a['makespan_delta_pct'])} vs the GPU-backed "
            f"baseline → {'WITHIN' if a['within_5pct'] else 'OUTSIDE'} the band.",
        ]
    (out / f"{prefix}report.md").write_text("\n".join(lines) + "\n")
