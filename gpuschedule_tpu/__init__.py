"""gpuschedule_tpu — a TPU-native deep-learning cluster scheduling framework.

A ground-up rebuild of the capabilities of matthewygf/GPUSchedule for TPU pods:
a trace-replay simulator (Microsoft Philly trace + synthetic Poisson workloads)
evaluating scheduling/placement/preemption policies (FIFO, SRTF, Tiresias-LAS,
Gandiva, Optimus) over contiguous TPU v5e/v5p sub-mesh ("slice") allocations,
plus an online per-job throughput profiler implemented as a JAX/XLA step-time
harness over ICI (replacing the reference's torch.distributed + NCCL allreduce
microbenchmarks).

Provenance note: `/root/reference` was an empty mount during both the survey and
build sessions (see SURVEY.md §0), so docstrings in this package cite SURVEY.md
sections and BASELINE.json lines instead of reference `file:line`.

Layering (SURVEY.md §1):
    sim/        job model, trace replay, discrete-event engine, metrics
    cluster/    TPU torus topology + contiguous slice allocator (+ GPU model
                for the topology-aware comparison config)
    policies/   FIFO, SRTF, Tiresias-DLAS, Gandiva, Optimus
    placement/  consolidated / random / greedy / topology-aware /
                contention-aware schemes
    faults/     fault injection & recovery: seeded chip/slice failure
                schedules, checkpoint-rollback recovery, MTBF robustness
                sweeps (engine _FAULT/_REPAIR events + cluster health masks)
    net/        shared-fabric DCN contention model: per-pod uplinks + an
                oversubscribed aggregation core, max-min fair bandwidth
                shares driving dynamic multislice speed factors, link
                faults, link-level telemetry
    obs/        span tracer, metrics registry, Perfetto trace export
    profiler/   JAX step-time harness, ICI cost model, goodput curve fitting
    models/     flax benchmark models driven by the profiler
    parallel/   mesh construction + sharded train steps (dp/tp/sp)
    ops/        pallas TPU kernels for the benchmark models
"""

__version__ = "0.5.1"
