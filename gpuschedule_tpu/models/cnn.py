"""Residual CNN classifier in flax, MXU-first.

The vision-model family of the zoo (see :class:`~gpuschedule_tpu.models
.config.CnnConfig`): Philly's workload is CNN-heavy and the reference's
profiler benchmarks real vision models (SURVEY.md §2 "Throughput
profiler").  Same hardware rules as the transformer zoo: bf16 compute /
f32 params so convs tile onto the MXU, static shapes, GroupNorm instead of
BatchNorm so ``apply`` is pure (no mutable batch stats — the train step
stays a plain ``jax.jit`` with donated state, and normalization is
independent of the dp shard size).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from gpuschedule_tpu.models.config import CnnConfig


class ResBlock(nn.Module):
    """3x3-3x3 residual block, pre-norm, bf16 compute."""

    ch: int
    stride: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        h = nn.GroupNorm(num_groups=8, dtype=jnp.bfloat16, name="gn1")(x)
        h = nn.relu(h)
        h = nn.Conv(
            self.ch, (3, 3), strides=(self.stride, self.stride),
            dtype=jnp.bfloat16, param_dtype=jnp.float32, name="conv1",
        )(h)
        h = nn.GroupNorm(num_groups=8, dtype=jnp.bfloat16, name="gn2")(h)
        h = nn.relu(h)
        h = nn.Conv(
            self.ch, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32,
            name="conv2",
        )(h)
        if x.shape[-1] != self.ch or self.stride != 1:
            x = nn.Conv(
                self.ch, (1, 1), strides=(self.stride, self.stride),
                dtype=jnp.bfloat16, param_dtype=jnp.float32, name="proj",
            )(x)
        return x + h


class ResNet(nn.Module):
    """Stem → stages (downsample 2x, widen) → pooled linear head."""

    cfg: CnnConfig

    @nn.compact
    def __call__(self, images: jax.Array) -> jax.Array:
        c = self.cfg
        x = images.astype(jnp.bfloat16)
        x = nn.Conv(
            c.channels[0], (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32,
            name="stem",
        )(x)
        for si, ch in enumerate(c.channels):
            for bi in range(c.blocks_per_stage):
                stride = 2 if (bi == 0 and si > 0) else 1
                x = ResBlock(ch, stride, name=f"s{si}b{bi}")(x)
        x = nn.GroupNorm(num_groups=8, dtype=jnp.bfloat16, name="gn_f")(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        logits = nn.Dense(
            c.num_classes, dtype=jnp.bfloat16, param_dtype=jnp.float32,
            name="head",
        )(x)
        return logits.astype(jnp.float32)
