"""Model configs — jax-free so the sim layer can import them.

The dataclasses here carry everything the *simulator* needs about a model
(parameter count, FLOPs estimate) without touching flax/jax; the actual
modules live in :mod:`gpuschedule_tpu.models.transformer` and are imported
lazily by the package ``__getattr__`` (the sim core must stay importable
with no accelerator stack present — SURVEY.md §4 "no GPU in the loop").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab: int = 8192
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    max_seq: int = 512
    remat: bool = False
    # n_experts > 0 turns each block's MLP into a routed MoE
    # (models/transformer.py MoeMlp, experts sharded over the tp axis)
    n_experts: int = 0
    capacity_factor: float = 1.25
    # experts each token routes to (1 = Switch, 2 = GShard-style top-2)
    router_top_k: int = 1
    # router z-loss coefficient RELATIVE to the trainer's moe_aux_weight
    # (it rides the same sown channel as the load-balancing aux): the
    # effective loss term is moe_aux_weight * router_z_weight * z, with
    # z = mean(logsumexp(router_logits)^2).  0 disables.
    router_z_weight: float = 0.0

    def __post_init__(self):
        # active_param_count subtracts (n_experts - router_top_k) FFN
        # copies; an out-of-range k would silently skew every FLOPs/MFU/
        # goodput figure while MoeMlp clamps or raises — fail here so the
        # two can never disagree
        if self.n_experts and not (1 <= self.router_top_k <= self.n_experts):
            raise ValueError(
                f"router_top_k={self.router_top_k} must be in "
                f"[1, n_experts={self.n_experts}]"
            )

    @property
    def param_count(self) -> int:
        """Approximate parameter count (embeddings + blocks).  MoE configs
        hold n_experts copies of each FFN plus a router."""
        ffn = 2 * self.d_model * self.d_ff
        per_block = 4 * self.d_model * self.d_model + max(1, self.n_experts) * ffn
        if self.n_experts:
            per_block += self.d_model * self.n_experts  # router
        return self.vocab * self.d_model + self.n_layers * per_block

    @property
    def active_param_count(self) -> int:
        """Params a single token actually exercises: for top-k MoE that is
        k expert FFNs per block (plus the router), not all n_experts —
        the count FLOPs and goodput estimates must use.  Derived from
        ``param_count`` (single source of the arithmetic): the inactive
        mass is exactly the n_experts-k unused FFN copies per block."""
        if not self.n_experts:
            return self.param_count
        ffn = 2 * self.d_model * self.d_ff
        inactive = max(0, self.n_experts - self.router_top_k)
        return self.param_count - self.n_layers * inactive * ffn

    def flops_per_token(self) -> float:
        """~6N FLOPs/token for fwd+bwd, N = ACTIVE params (equals total
        params for dense configs; router_top_k experts per token for MoE — the
        standard estimate the MFU arithmetic in bench.py uses)."""
        return 6.0 * self.active_param_count

    def flops_per_token_attn(self, seq_len: int) -> float:
        """6N plus the causal-attention matmul FLOPs, which 6N ignores and
        which dominate at long context: 12·L·S·d fwd+bwd per token, halved
        for causal masking → 6·L·S·d.  Use this for long-context MFU
        (at S=32k it is ~5x the 6N figure for the xlong config)."""
        return self.flops_per_token() + 6.0 * self.n_layers * seq_len * self.d_model


@dataclass(frozen=True)
class CnnConfig:
    """Residual CNN classifier — the vision-model family.

    Philly's workload is dominated by CNN training jobs, and the reference
    profiles real vision models through its DDP microbenchmarks (SURVEY.md
    §2 "Throughput profiler"); this config family plays that role.  Stages
    halve resolution and grow channels ResNet-style.
    """

    name: str
    channels: tuple = (64, 128, 256)
    blocks_per_stage: int = 2
    image_size: int = 32
    num_classes: int = 100

    @property
    def param_count(self) -> int:
        """Approximate parameter count (3x3 conv pairs per block + head)."""
        total = 3 * 3 * 3 * self.channels[0]          # stem
        prev = self.channels[0]
        for ch in self.channels:
            # per stage: entry conv (prev->ch) + (2*blocks - 1) ch->ch convs
            total += 3 * 3 * prev * ch
            total += (2 * self.blocks_per_stage - 1) * 3 * 3 * ch * ch
            prev = ch
        return total + prev * self.num_classes        # linear head

    def flops_per_token(self) -> float:
        """FLOPs per *sample* (fwd+bwd); named for interface parity with
        :class:`ModelConfig` so MFU/goodput arithmetic is uniform.  Conv
        FLOPs = 2 * k*k * cin * cout * H*W per layer, x3 for fwd+bwd."""
        hw = self.image_size * self.image_size
        fl = 2 * 3 * 3 * 3 * self.channels[0] * hw
        prev = self.channels[0]
        for ch in self.channels:
            fl += 2 * 3 * 3 * prev * ch * hw
            fl += (2 * self.blocks_per_stage - 1) * 2 * 3 * 3 * ch * ch * hw
            hw //= 4  # stage downsamples 2x in each spatial dim
            prev = ch
        return 3.0 * fl


# Unknown-model fallback shared by every consumer that must price a job
# whose trace-supplied model name is not in the zoo (straight-from-Philly
# workload names): the zoo median, transformer-small.  Before this existed,
# cluster/tpu.py hardcoded a 30M-param default while sim/overhead.py fell
# back to the zoo median — the same Philly job paid a DCN toll and a
# restore cost derived from two different phantom models.
FALLBACK_MODEL = "transformer-small"


def resolve_model_config(model_name) -> "ModelConfig | CnnConfig":
    """The config for ``model_name``, or the shared :data:`FALLBACK_MODEL`
    config when the name is unknown (or None).  Single source of the
    unknown-model fallback: DCN toll (cluster/tpu.py), restore cost
    (sim/overhead.py), and network demand (net/) all agree on what a
    nameless job "is"."""
    cfg = MODEL_CONFIGS.get(model_name)
    return cfg if cfg is not None else MODEL_CONFIGS[FALLBACK_MODEL]


# Both families expose the same estimate interface — ``param_count`` and
# ``flops_per_token()`` (per-token for LMs, per-SAMPLE for CNNs) — which the
# goodput, overhead, and bench arithmetic depend on.
MODEL_CONFIGS: Dict[str, "ModelConfig | CnnConfig"] = {
    cfg.name: cfg
    for cfg in (
        CnnConfig("resnet-tiny", channels=(32, 64), blocks_per_stage=1),
        CnnConfig("resnet-mid", channels=(64, 128, 256), blocks_per_stage=2),
        ModelConfig("transformer-tiny", d_model=128, n_layers=2, n_heads=4, d_ff=512),
        ModelConfig("transformer-small", d_model=256, n_layers=4, n_heads=8, d_ff=1024),
        ModelConfig("transformer-base", d_model=512, n_layers=8, n_heads=8, d_ff=2048),
        # Flagship bench config: sized so the per-layer matmuls fill the MXU
        # on one chip — measured 62% MFU at (b8, s512) on v5e vs 33% for
        # transformer-base, the knee of the d_model sweep (1024: 47%,
        # 1536x8: 59%, 2048x8: 60%, 1536x12: 62%).
        ModelConfig(
            "transformer-large", d_model=1536, n_layers=12, n_heads=16, d_ff=6144
        ),
        ModelConfig(
            "transformer-long",
            d_model=256,
            n_layers=4,
            n_heads=8,
            d_ff=1024,
            max_seq=4096,
            remat=True,
        ),
        # Long-context flagship: S=32k training fits one v5e chip ONLY via
        # the blockwise flash kernels (dense attention's (B, H, S, S) f32
        # scores are ~34 GB at S=32k — over 2x the chip's HBM) + remat.
        ModelConfig(
            "transformer-xlong",
            d_model=512,
            n_layers=6,
            n_heads=8,
            d_ff=2048,
            max_seq=32768,
            remat=True,
        ),
        # "mlp-wide" is a transformer with a fat FFN and thin attention —
        # keeps one model class while giving the profiler a compute-heavy,
        # communication-light point in the workload mix.
        ModelConfig("mlp-wide", d_model=256, n_layers=2, n_heads=2, d_ff=4096),
        # Mixture-of-experts family: top-1 (Switch) routing, experts
        # sharded over the tp mesh axis (expert parallelism).  8x the FFN
        # params of transformer-small at ~its per-token FLOPs.
        ModelConfig(
            "transformer-moe",
            d_model=256,
            n_layers=4,
            n_heads=8,
            d_ff=1024,
            n_experts=8,
        ),
        ModelConfig(
            "moe-tiny", d_model=128, n_layers=2, n_heads=4, d_ff=256,
            n_experts=4,
        ),
        # top-2 (GShard-style) variants: two experts per token with
        # renormalized gates + router z-loss for logit stability
        ModelConfig(
            "transformer-moe-top2",
            d_model=256, n_layers=4, n_heads=8, d_ff=1024, n_experts=8,
            router_top_k=2, router_z_weight=0.1, capacity_factor=2.0,
        ),
        ModelConfig(
            "moe-top2-tiny", d_model=128, n_layers=2, n_heads=4, d_ff=256,
            n_experts=4, router_top_k=2, router_z_weight=0.1,
            capacity_factor=2.0,
        ),
    )
}
