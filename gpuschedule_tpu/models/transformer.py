"""Decoder-only transformer LM in flax, written MXU-first.

Design for the hardware (task brief "Design for tpu hardware"):

- **bfloat16 compute, float32 params**: every matmul runs in bf16 on the
  MXU; the optimizer state and master weights stay f32.
- **Static shapes everywhere**: batch and sequence length are fixed at
  trace time so XLA compiles one program; no data-dependent control flow.
- **Fusible structure**: plain LN → attention → residual → LN → MLP →
  residual chains that XLA fuses into a handful of kernels; no hand
  scheduling.
- **Remat-friendly**: each block is wrapped in ``jax.checkpoint`` when
  ``remat=True`` so long-sequence configs trade FLOPs for HBM.
- **Sharding-agnostic**: modules never mention a mesh.  Parallelism comes
  from the partition specs in :mod:`gpuschedule_tpu.parallel` (megatron-
  style column/row split of the MLP and attention projections), applied
  from outside via ``NamedSharding`` — XLA inserts the collectives.

The reference profiles torch models over DDP (SURVEY.md §3.2 starred
block); this zoo plays that role for the JAX harness.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from gpuschedule_tpu.models.config import MODEL_CONFIGS, CnnConfig, ModelConfig


class ProjectedAttention(nn.Module):
    """QKV/out projections around an externally supplied attention core
    (ring attention for sequence-sharded long context).  Param names mirror
    ``nn.SelfAttention`` (query/key/value/out) so the megatron tp partition
    rules in :func:`gpuschedule_tpu.parallel.train.param_partition_spec`
    apply unchanged."""

    cfg: ModelConfig
    attn_fn: Any

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        if c.d_model % c.n_heads != 0:
            # nn.SelfAttention enforces this on the dense path; keep the
            # ring path structurally identical instead of silently flooring
            raise ValueError(
                f"d_model {c.d_model} not divisible by n_heads {c.n_heads}"
            )
        head = c.d_model // c.n_heads
        proj = dict(dtype=jnp.bfloat16, param_dtype=jnp.float32)
        q = nn.DenseGeneral(features=(c.n_heads, head), name="query", **proj)(x)
        k = nn.DenseGeneral(features=(c.n_heads, head), name="key", **proj)(x)
        v = nn.DenseGeneral(features=(c.n_heads, head), name="value", **proj)(x)
        o = self.attn_fn(q, k, v)  # (B, S, H, head)
        return nn.DenseGeneral(
            features=c.d_model, axis=(-2, -1), name="out", **proj
        )(o)


class MoeMlp(nn.Module):
    """Top-k routed mixture-of-experts FFN (k=1: Switch; k=2: GShard).

    TPU-native by construction: routing is expressed as dense one-hot
    **dispatch/combine einsums** over an (experts, capacity, d) buffer —
    no scatter/gather, so everything lands on the MXU and the whole layer
    shards by annotating the expert dim.  The partition rule in
    :func:`gpuschedule_tpu.parallel.train.param_partition_spec` puts the
    expert dim of ``w_up``/``w_down`` on the **tp axis** (expert
    parallelism over the tensor axis — ep-over-tp); XLA turns the
    dispatch einsum's sharding mismatch into the all-to-all the GShard
    paper inserts by hand.

    Tokens route to their top ``cfg.router_top_k`` experts (f32 router
    math for stable training); for k > 1 the kept gates renormalize to
    sum to one.  Each expert processes at most ``capacity_factor * T / E``
    tokens; overflow choices are dropped (that choice's contribution is
    0, so the residual stream carries the token through — standard
    Switch/GShard behavior).  Later choices queue behind earlier ones:
    a token's second expert slot is assigned after every token's first
    choice, GShard's sequential-capacity rule.

    Sown losses (one ``moe_losses`` channel, consumed by both trainers at
    ``moe_aux_weight``): the load-balancing aux ``E * sum_e f_e * P_e``
    (f_e = fraction of routed choices to e, P_e = mean router prob;
    minimized at uniform routing) plus, when ``cfg.router_z_weight > 0``,
    the router z-loss ``mean(logsumexp(logits)^2)`` scaled by that
    coefficient — it keeps router logits from drifting large, where bf16
    softmax saturates and routing gradients vanish.
    """

    cfg: ModelConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        b, s, d = x.shape
        e = c.n_experts
        k = max(1, int(c.router_top_k))
        if k > e:
            raise ValueError(f"router_top_k={k} exceeds n_experts={e}")
        t = b * s
        cap = max(1, int(c.capacity_factor * t / e))

        logits = nn.Dense(
            e, dtype=jnp.float32, param_dtype=jnp.float32, name="router"
        )(x.astype(jnp.float32))
        logits = logits.reshape(t, e)
        probs = jax.nn.softmax(logits, axis=-1)
        top_probs, top_idx = jax.lax.top_k(probs, k)        # (T, k)
        if k > 1:
            gates = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)
        else:
            gates = top_probs  # Switch keeps the raw argmax prob
        onehots = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # (T, k, E)
        # load-balancing aux over ALL routed choices (k=1 reduces to the
        # Switch formula): minimized (= 1) at uniform routing
        frac = jnp.mean(jnp.sum(onehots, axis=1), axis=0)  # (E,) choices/e / T
        mean_prob = jnp.mean(probs, axis=0)
        aux = e / k * jnp.sum(frac * mean_prob)
        if c.router_z_weight > 0.0:
            z = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
            aux = aux + c.router_z_weight * z
        self.sow("moe_losses", "aux", aux)
        # slot assignment, choice-major (GShard): all first choices claim
        # capacity before any second choice.  Within one choice rank j,
        # the chosen column of cumsum holds count-1 (>= 0), others -1,
        # so the row max extracts it (a row SUM would add the -1s).
        base = jnp.zeros((e,), jnp.float32)   # slots already claimed per expert
        dispatch = jnp.zeros((t, e, cap), jnp.bfloat16)
        combine = jnp.zeros((t, e, cap), jnp.bfloat16)
        for j in range(k):                    # static unroll, k is tiny
            oh = onehots[:, j, :]                            # (T, E)
            pos = (jnp.cumsum(oh, axis=0) + base[None, :]) * oh - 1.0
            pos_tok = jnp.max(pos, axis=-1)                  # (T,) >= 0 if chosen
            keep = (pos_tok >= 0) & (pos_tok < cap)
            pos_clip = jnp.clip(pos_tok, 0, cap - 1).astype(jnp.int32)
            dj = (
                oh[:, :, None]
                * jax.nn.one_hot(pos_clip, cap, dtype=jnp.float32)[:, None, :]
                * keep[:, None, None]
            )
            dispatch = dispatch + dj.astype(jnp.bfloat16)
            combine = combine + (dj * gates[:, j, None, None]).astype(jnp.bfloat16)
            base = base + jnp.sum(oh, axis=0)
        xf = x.reshape(t, d)
        expert_in = jnp.einsum("td,tec->ecd", xf.astype(jnp.bfloat16), dispatch)

        kin = nn.initializers.lecun_normal()
        w_up = self.param("w_up", kin, (e, d, c.d_ff), jnp.float32)
        b_up = self.param("b_up", nn.initializers.zeros, (e, c.d_ff), jnp.float32)
        w_down = self.param("w_down", kin, (e, c.d_ff, d), jnp.float32)
        b_down = self.param("b_down", nn.initializers.zeros, (e, d), jnp.float32)
        h = (
            jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(jnp.bfloat16))
            + b_up[:, None, :].astype(jnp.bfloat16)
        )
        h = nn.gelu(h)
        out = (
            jnp.einsum("ecf,efd->ecd", h, w_down.astype(jnp.bfloat16))
            + b_down[:, None, :].astype(jnp.bfloat16)
        )
        # combine: gather each token's k slots back, gate-weighted
        y = jnp.einsum("ecd,tec->td", out, combine)
        return y.reshape(b, s, d)


class Block(nn.Module):
    """Pre-LN causal self-attention + MLP block, bf16 compute.  The MLP is
    a dense FFN, or a top-``router_top_k`` MoE when the config sets
    ``n_experts``."""

    cfg: ModelConfig
    attn_fn: Any = None  # None -> dense SelfAttention; else (q,k,v)->out core

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        h = nn.LayerNorm(dtype=jnp.bfloat16, name="ln1")(x)
        if self.attn_fn is not None:
            h = ProjectedAttention(c, self.attn_fn, name="attn")(h)
        else:
            h = nn.SelfAttention(
                num_heads=c.n_heads,
                qkv_features=c.d_model,
                dtype=jnp.bfloat16,
                param_dtype=jnp.float32,
                deterministic=True,
                name="attn",
            )(h, mask=nn.make_causal_mask(jnp.zeros(h.shape[:2], dtype=jnp.int32)))
        x = x + h
        h = nn.LayerNorm(dtype=jnp.bfloat16, name="ln2")(x)
        if c.n_experts:
            return x + MoeMlp(c, name="moe")(h)
        h = nn.Dense(c.d_ff, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="up")(h)
        h = nn.gelu(h)
        h = nn.Dense(c.d_model, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="down")(h)
        return x + h


class Embedder(nn.Module):
    """Token + position embedding — the pre-pipeline boundary of a staged
    LM (parallel/pipeline.py PipelinedLM); param names match
    :class:`TransformerLM` so the partition rules apply unchanged."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        c = self.cfg
        x = nn.Embed(
            c.vocab, c.d_model, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="embed"
        )(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (c.max_seq, c.d_model),
            jnp.float32,
        )
        return x + pos[None, : tokens.shape[1], :].astype(jnp.bfloat16)


class LMHead(nn.Module):
    """Final LN + logits — the post-pipeline boundary of a staged LM."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        x = nn.LayerNorm(dtype=jnp.bfloat16, name="ln_f")(x)
        logits = nn.Dense(
            c.vocab, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)  # f32 softmax for stable loss


class TransformerLM(nn.Module):
    """Causal LM: embed → blocks → final LN → logits (tied to f32 head)."""

    cfg: ModelConfig
    attn_fn: Any = None  # optional attention core (e.g. ring attention)

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        c = self.cfg
        x = nn.Embed(
            c.vocab, c.d_model, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="embed"
        )(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (c.max_seq, c.d_model),
            jnp.float32,
        )
        x = x + pos[None, : tokens.shape[1], :].astype(jnp.bfloat16)
        block = Block
        if c.remat:
            block = nn.remat(Block)  # trade FLOPs for HBM on long sequences
        for i in range(c.n_layers):
            x = block(c, self.attn_fn, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=jnp.bfloat16, name="ln_f")(x)
        logits = nn.Dense(
            c.vocab, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)  # f32 softmax for stable loss


def build_model(name: str, *, attn_fn=None):
    """Look up a config by trace model name and build its module
    (transformer LM or CNN classifier, per the config family).

    ``attn_fn`` swaps the LM attention core — the trainer passes ring
    attention here for sequence-sharded long-context runs."""
    try:
        cfg = MODEL_CONFIGS[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; known: {sorted(MODEL_CONFIGS)}") from None
    if isinstance(cfg, CnnConfig):
        if attn_fn is not None:
            raise ValueError("attn_fn applies to transformer LMs, not CNNs")
        from gpuschedule_tpu.models.cnn import ResNet

        return ResNet(cfg), cfg
    return TransformerLM(cfg, attn_fn), cfg
