"""Decoder-only transformer LM in flax, written MXU-first.

Design for the hardware (task brief "Design for tpu hardware"):

- **bfloat16 compute, float32 params**: every matmul runs in bf16 on the
  MXU; the optimizer state and master weights stay f32.
- **Static shapes everywhere**: batch and sequence length are fixed at
  trace time so XLA compiles one program; no data-dependent control flow.
- **Fusible structure**: plain LN → attention → residual → LN → MLP →
  residual chains that XLA fuses into a handful of kernels; no hand
  scheduling.
- **Remat-friendly**: each block is wrapped in ``jax.checkpoint`` when
  ``remat=True`` so long-sequence configs trade FLOPs for HBM.
- **Sharding-agnostic**: modules never mention a mesh.  Parallelism comes
  from the partition specs in :mod:`gpuschedule_tpu.parallel` (megatron-
  style column/row split of the MLP and attention projections), applied
  from outside via ``NamedSharding`` — XLA inserts the collectives.

The reference profiles torch models over DDP (SURVEY.md §3.2 starred
block); this zoo plays that role for the JAX harness.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab: int = 8192
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    max_seq: int = 512
    remat: bool = False

    @property
    def param_count(self) -> int:
        """Approximate parameter count (embeddings + blocks)."""
        per_block = 4 * self.d_model * self.d_model + 2 * self.d_model * self.d_ff
        return self.vocab * self.d_model + self.n_layers * per_block

    def flops_per_token(self) -> float:
        """~6N FLOPs/token for fwd+bwd of an N-param dense LM (the standard
        estimate the MFU arithmetic in bench.py uses)."""
        return 6.0 * self.param_count


MODEL_CONFIGS: Dict[str, ModelConfig] = {
    cfg.name: cfg
    for cfg in (
        ModelConfig("transformer-tiny", d_model=128, n_layers=2, n_heads=4, d_ff=512),
        ModelConfig("transformer-small", d_model=256, n_layers=4, n_heads=8, d_ff=1024),
        ModelConfig("transformer-base", d_model=512, n_layers=8, n_heads=8, d_ff=2048),
        # Flagship bench config: sized so the per-layer matmuls fill the MXU
        # on one chip — measured 62% MFU at (b8, s512) on v5e vs 33% for
        # transformer-base, the knee of the d_model sweep (1024: 47%,
        # 1536x8: 59%, 2048x8: 60%, 1536x12: 62%).
        ModelConfig(
            "transformer-large", d_model=1536, n_layers=12, n_heads=16, d_ff=6144
        ),
        ModelConfig(
            "transformer-long",
            d_model=256,
            n_layers=4,
            n_heads=8,
            d_ff=1024,
            max_seq=4096,
            remat=True,
        ),
        # "mlp-wide" is a transformer with a fat FFN and thin attention —
        # keeps one model class while giving the profiler a compute-heavy,
        # communication-light point in the workload mix.
        ModelConfig("mlp-wide", d_model=256, n_layers=2, n_heads=2, d_ff=4096),
    )
}


class Block(nn.Module):
    """Pre-LN causal self-attention + MLP block, bf16 compute."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        h = nn.LayerNorm(dtype=jnp.bfloat16, name="ln1")(x)
        h = nn.SelfAttention(
            num_heads=c.n_heads,
            qkv_features=c.d_model,
            dtype=jnp.bfloat16,
            param_dtype=jnp.float32,
            deterministic=True,
            name="attn",
        )(h, mask=nn.make_causal_mask(jnp.zeros(h.shape[:2], dtype=jnp.int32)))
        x = x + h
        h = nn.LayerNorm(dtype=jnp.bfloat16, name="ln2")(x)
        h = nn.Dense(c.d_ff, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="up")(h)
        h = nn.gelu(h)
        h = nn.Dense(c.d_model, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="down")(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM: embed → blocks → final LN → logits (tied to f32 head)."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        c = self.cfg
        x = nn.Embed(
            c.vocab, c.d_model, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="embed"
        )(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (c.max_seq, c.d_model),
            jnp.float32,
        )
        x = x + pos[None, : tokens.shape[1], :].astype(jnp.bfloat16)
        block = Block
        if c.remat:
            block = nn.remat(Block)  # trade FLOPs for HBM on long sequences
        for i in range(c.n_layers):
            x = block(c, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=jnp.bfloat16, name="ln_f")(x)
        logits = nn.Dense(
            c.vocab, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)  # f32 softmax for stable loss


def build_model(name: str) -> Tuple[TransformerLM, ModelConfig]:
    """Look up a config by trace model name and build its module."""
    try:
        cfg = MODEL_CONFIGS[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; known: {sorted(MODEL_CONFIGS)}") from None
    return TransformerLM(cfg), cfg
