"""Decoder-only transformer LM in flax, written MXU-first.

Design for the hardware (task brief "Design for tpu hardware"):

- **bfloat16 compute, float32 params**: every matmul runs in bf16 on the
  MXU; the optimizer state and master weights stay f32.
- **Static shapes everywhere**: batch and sequence length are fixed at
  trace time so XLA compiles one program; no data-dependent control flow.
- **Fusible structure**: plain LN → attention → residual → LN → MLP →
  residual chains that XLA fuses into a handful of kernels; no hand
  scheduling.
- **Remat-friendly**: each block is wrapped in ``jax.checkpoint`` when
  ``remat=True`` so long-sequence configs trade FLOPs for HBM.
- **Sharding-agnostic**: modules never mention a mesh.  Parallelism comes
  from the partition specs in :mod:`gpuschedule_tpu.parallel` (megatron-
  style column/row split of the MLP and attention projections), applied
  from outside via ``NamedSharding`` — XLA inserts the collectives.

The reference profiles torch models over DDP (SURVEY.md §3.2 starred
block); this zoo plays that role for the JAX harness.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from gpuschedule_tpu.models.config import MODEL_CONFIGS, CnnConfig, ModelConfig


class ProjectedAttention(nn.Module):
    """QKV/out projections around an externally supplied attention core
    (ring attention for sequence-sharded long context).  Param names mirror
    ``nn.SelfAttention`` (query/key/value/out) so the megatron tp partition
    rules in :func:`gpuschedule_tpu.parallel.train.param_partition_spec`
    apply unchanged."""

    cfg: ModelConfig
    attn_fn: Any

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        if c.d_model % c.n_heads != 0:
            # nn.SelfAttention enforces this on the dense path; keep the
            # ring path structurally identical instead of silently flooring
            raise ValueError(
                f"d_model {c.d_model} not divisible by n_heads {c.n_heads}"
            )
        head = c.d_model // c.n_heads
        proj = dict(dtype=jnp.bfloat16, param_dtype=jnp.float32)
        q = nn.DenseGeneral(features=(c.n_heads, head), name="query", **proj)(x)
        k = nn.DenseGeneral(features=(c.n_heads, head), name="key", **proj)(x)
        v = nn.DenseGeneral(features=(c.n_heads, head), name="value", **proj)(x)
        o = self.attn_fn(q, k, v)  # (B, S, H, head)
        return nn.DenseGeneral(
            features=c.d_model, axis=(-2, -1), name="out", **proj
        )(o)


class Block(nn.Module):
    """Pre-LN causal self-attention + MLP block, bf16 compute."""

    cfg: ModelConfig
    attn_fn: Any = None  # None -> dense SelfAttention; else (q,k,v)->out core

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        c = self.cfg
        h = nn.LayerNorm(dtype=jnp.bfloat16, name="ln1")(x)
        if self.attn_fn is not None:
            h = ProjectedAttention(c, self.attn_fn, name="attn")(h)
        else:
            h = nn.SelfAttention(
                num_heads=c.n_heads,
                qkv_features=c.d_model,
                dtype=jnp.bfloat16,
                param_dtype=jnp.float32,
                deterministic=True,
                name="attn",
            )(h, mask=nn.make_causal_mask(jnp.zeros(h.shape[:2], dtype=jnp.int32)))
        x = x + h
        h = nn.LayerNorm(dtype=jnp.bfloat16, name="ln2")(x)
        h = nn.Dense(c.d_ff, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="up")(h)
        h = nn.gelu(h)
        h = nn.Dense(c.d_model, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="down")(h)
        return x + h


class TransformerLM(nn.Module):
    """Causal LM: embed → blocks → final LN → logits (tied to f32 head)."""

    cfg: ModelConfig
    attn_fn: Any = None  # optional attention core (e.g. ring attention)

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        c = self.cfg
        x = nn.Embed(
            c.vocab, c.d_model, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="embed"
        )(tokens)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (c.max_seq, c.d_model),
            jnp.float32,
        )
        x = x + pos[None, : tokens.shape[1], :].astype(jnp.bfloat16)
        block = Block
        if c.remat:
            block = nn.remat(Block)  # trade FLOPs for HBM on long sequences
        for i in range(c.n_layers):
            x = block(c, self.attn_fn, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=jnp.bfloat16, name="ln_f")(x)
        logits = nn.Dense(
            c.vocab, dtype=jnp.bfloat16, param_dtype=jnp.float32, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)  # f32 softmax for stable loss


def build_model(name: str, *, attn_fn=None):
    """Look up a config by trace model name and build its module
    (transformer LM or CNN classifier, per the config family).

    ``attn_fn`` swaps the LM attention core — the trainer passes ring
    attention here for sequence-sharded long-context runs."""
    try:
        cfg = MODEL_CONFIGS[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; known: {sorted(MODEL_CONFIGS)}") from None
    if isinstance(cfg, CnnConfig):
        if attn_fn is not None:
            raise ValueError("attn_fn applies to transformer LMs, not CNNs")
        from gpuschedule_tpu.models.cnn import ResNet

        return ResNet(cfg), cfg
    return TransformerLM(cfg, attn_fn), cfg
