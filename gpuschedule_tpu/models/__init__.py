"""Benchmark model zoo driven by the profiler and bench harness.

The reference's profiler microbenchmarks run a real model forward/backward
per candidate world size (SURVEY.md §2 "Throughput profiler"); these are the
TPU-native equivalents: small flax models with static shapes and bfloat16
compute so XLA tiles every matmul onto the MXU.  Names match the model
names emitted by the trace generators (sim/trace.py DEFAULT_MODELS) so a
simulated job maps directly onto a profilable model.

Configs (:mod:`config`) are jax-free and import eagerly; the flax modules
load lazily on first attribute access so the sim layer can consume
``MODEL_CONFIGS`` (param counts for overhead/goodput models) without
pulling in the accelerator stack.
"""

from gpuschedule_tpu.models.config import MODEL_CONFIGS, ModelConfig

__all__ = ["MODEL_CONFIGS", "ModelConfig", "TransformerLM", "build_model"]


def __getattr__(name: str):
    if name in ("TransformerLM", "build_model"):
        from gpuschedule_tpu.models import transformer

        return getattr(transformer, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
