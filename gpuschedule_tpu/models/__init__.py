"""Benchmark model zoo driven by the profiler and bench harness.

The reference's profiler microbenchmarks run a real model forward/backward
per candidate world size (SURVEY.md §2 "Throughput profiler"); these are the
TPU-native equivalents: small flax models with static shapes and bfloat16
compute so XLA tiles every matmul onto the MXU.  Names match the model
names emitted by the trace generators (sim/trace.py DEFAULT_MODELS) so a
simulated job maps directly onto a profilable model.
"""

from gpuschedule_tpu.models.transformer import (
    MODEL_CONFIGS,
    ModelConfig,
    TransformerLM,
    build_model,
)

__all__ = ["MODEL_CONFIGS", "ModelConfig", "TransformerLM", "build_model"]
